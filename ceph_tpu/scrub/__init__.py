"""TPU-native integrity engine — batched CRC32C + EC parity deep-scrub.

The reference OSD's deep scrub (``src/osd/scrubber/``, backed by
``ceph_crc32c``) recomputes per-object digests, cross-checks
replicas/shards, and drives repair.  Here the digest math itself is
GF(2) linear algebra batched on the accelerator:

- :mod:`.crc32c_jax` — true CRC32C (Castagnoli, poly ``0x1EDC6F41``
  reflected) as a bit-matrix kernel over ``[n_objects, chunk]`` uint8
  batches, plus ``crc32c_combine`` via matrix exponentiation so
  chunked CRCs merge exactly like the reference's buffer-chain CRC;
- :mod:`.engine` — the batched deep-scrub planner: groups shard
  payloads, digests them on-device, and for EC pools recomputes
  parity through the existing ``ops/gf_jax`` matmul path to catch
  bit-rot that per-shard digest self-checks cannot see.
"""

from .crc32c_jax import crc32c, crc32c_combine, crc32c_batch  # noqa: F401
from .engine import ScrubEngine, default_engine  # noqa: F401

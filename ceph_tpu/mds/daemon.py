"""MDS daemon — the CephFS metadata server.

Reference behavior re-created (``src/mds/MDSRank.cc``, ``Server.cc``,
``MDCache.cc``, ``MDLog.cc``; SURVEY.md §3.9):

- **standby → active**: beacons to the mons; the MDSMonitor promotes a
  standby into a filesystem's rank 0 and everyone learns it from the
  FSMap (beacon timeout = failover, reference MDSMonitor::tick);
- **metadata in RADOS**: each directory is a *dirfrag object* in the
  metadata pool (``<ino-hex>.00000000``) whose omap maps dentry name →
  inode record — the reference's CDir backing store exactly;
- **write-ahead journal** (reference MDLog): every mutation appends an
  event to the journal object's omap and is acknowledged from the
  journal, not the dirfrags; dirty dirfrags flush lazily and the
  journal trims behind them.  A newly-active MDS **replays** the
  journal into the backing store before serving — metadata acked
  before a crash survives the failover;
- **sessions + request dedup**: journal events carry (client, tid);
  replay rebuilds the completed-request set so a client resending
  across a failover gets its original answer, not EEXIST (reference
  session completed_requests);
- **never on the data path**: file bytes flow client↔OSD through the
  striper; the MDS only tracks size/mtime via setattr (cap flush
  analog) and purges data objects on unlink.
"""

from __future__ import annotations

import json
import threading
import time

from ..mon import messages as MM
from ..mon.client import MonClient
from ..msg import Dispatcher, Messenger
from ..osdc.librados import IoCtx, ObjectNotFound, Rados
from . import messages as M
from .fsmap import FSMap, STATE_ACTIVE

ROOT_INO = 1
INO_CHUNK = 128          # inode numbers claimed per journal event
JHEAD = "mds{rank}_journal"
INOTABLE = "mds{rank}_inotable"
INODES = "mds_inodes"   # multi-link inode rows (size/mtime/nlink) —
# SHARED across ranks: ino spaces are rank-disjoint so rows never
# collide, and a subtree re-homed by a max_mds change keeps its
# hard-link state visible to the new owner


def dirfrag_oid(ino: int, frag: int = 0) -> str:
    """Fragment object of a directory (reference CDir backing store:
    ``<ino-hex>.<frag-hex>``); fragment 0 is also where an over-size
    directory's fragtree row lives."""
    return f"{ino:x}.{frag:08x}"


# the fragtree row inside fragment 0's omap.  NUL is illegal in a
# dentry name, so this key can never collide with a real entry.
FRAGTREE_KEY = "\x00fragtree"
DIRFRAG_MAX = 256               # split ceiling (2^8 fragments)


def frag_of(name: str, nfrags: int) -> int:
    """Dentry → fragment (reference ceph_frag hash placement; a
    power-of-two modulo keeps redistribution local on split: a row in
    frag f moves to f or f+old_n, nowhere else)."""
    import zlib
    return zlib.crc32(name.encode()) % nfrags if nfrags > 1 else 0


def data_oid(ino: int, objno: int) -> str:
    """File data object name (reference ``<ino-hex>.<objno-08x>``)."""
    return f"{ino:x}.{objno:08x}"


SNAPS_OID = "mds_snaps"     # registry omap: "<dino-hex>\0<name>" → json


def snap_manifest_oid(snapid: int, ino: int) -> str:
    """Frozen dentry table of directory `ino` as of snapshot
    `snapid` (reference: snapshotted metadata lives in the dirfrag
    objects keyed by snapid; a separate manifest object is the
    eager-copy analog)."""
    return f"snapmeta.{snapid}.{ino:x}"


def _now() -> float:
    return time.time()


class MDSDaemon(Dispatcher):
    def __init__(self, name: str, monmap, *,
                 beacon_interval: float = 0.4,
                 flush_interval: float = 2.0, auth=None):
        self.name = name
        self.monmap = monmap
        self.auth = auth
        self.beacon_interval = beacon_interval
        self.flush_interval = flush_interval
        self.monc = MonClient(monmap, entity=f"mds.{name}",
                              auth=auth)
        self.msgr = Messenger(
            f"mds.{name}",
            **(auth.msgr_kwargs(f"mds.{name}") if auth else {}))
        self.msgr.add_dispatcher(self)
        self.lock = threading.RLock()
        self.state = "boot"           # boot / standby / active
        self.fsmap = FSMap()
        self.rank = -1
        self.fscid = -1
        self.addr = None
        self.running = False
        self._beacon_seq = 0
        self._thread: threading.Thread | None = None
        # active-state machinery
        self.rados: Rados | None = None
        self.meta: IoCtx | None = None
        self.data: IoCtx | None = None
        # dir ino → {dentry: inode record}; dirty deltas per dir
        self._dirs: dict[int, dict[str, dict]] = {}
        self._frags_cache: dict[int, int] = {}
        # split a dirfrag when its entry count exceeds this
        # (reference mds_bal_split_size)
        self.dirfrag_split_size = 10000
        self._dirty_set: dict[int, dict[str, dict]] = {}
        self._dirty_rm: dict[int, set[str]] = {}
        self._jseq = 0                # next journal event seq
        self._jfirst = 0              # lowest unflushed journal seq
        self._completed: dict[tuple[str, int], dict] = {}
        self._next_ino = 0
        self._ino_limit = 0
        self._last_flush = 0.0
        self.sessions: dict[str, int] = {}
        # observability (reference: every daemon has PerfCounters +
        # an AdminSocket — `ceph daemon mds.X perf dump / session ls`)
        from ..core.admin_socket import AdminSocket, default_path
        from ..core.perf_counters import PerfCountersBuilder
        pb = PerfCountersBuilder(f"mds.{name}")
        pb.add_u64_counter("request", "client requests served")
        pb.add_u64_counter("reply", "client replies sent")
        pb.add_u64_counter("journal_events", "journal events appended")
        pb.add_u64_counter("replays", "journal replays performed")
        self.perf = pb.create_perf_counters()
        self.admin_socket = AdminSocket(default_path(f"mds.{name}"))
        self.admin_socket.register(
            "perf dump", lambda c: self.perf.dump(),
            "dump perf counters")
        self.admin_socket.register(
            "status", lambda c: {
                "name": self.name, "state": self.state,
                "rank": self.rank, "fscid": self.fscid,
                "journal_seq": self._jseq,
                "cached_dirs": len(self._dirs)},
            "daemon status")
        self.admin_socket.register(
            "session ls", lambda c: [
                {"client": cl, "seq": seq}
                for cl, seq in sorted(self.sessions.items())],
            "open client sessions")
        from ..core.mempool import dump_mempools
        self.admin_socket.register(
            "dump_mempools", lambda c: dump_mempools(),
            "per-pool allocation accounting")

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self.addr = self.msgr.bind()
        self.admin_socket.start()
        self.running = True
        self.monc.on_fsmap = self._on_fsmap
        self.monc.sub_want("fsmap", 0)
        self._send_beacon()
        self.state = "standby"
        self._thread = threading.Thread(
            target=self._beacon_loop, name=f"mds.{self.name}-beacon",
            daemon=True)
        self._thread.start()
        return self

    def shutdown(self):
        self.running = False
        with self.lock:
            if self.state == "active":
                try:
                    self._flush(trim=True)
                except Exception:     # noqa: BLE001 — fs may be gone
                    pass
        if self.rados is not None:
            self.rados.shutdown()
            self.rados = None
        self.admin_socket.shutdown()
        self.monc.shutdown()
        self.msgr.shutdown()

    def kill(self):
        """Hard-stop without flushing — the failover test's crash:
        journaled-but-unflushed metadata must survive via replay."""
        self.running = False
        self.admin_socket.shutdown()
        if self.rados is not None:
            self.rados.shutdown()
            self.rados = None
        self.monc.shutdown()
        self.msgr.shutdown()

    def _send_beacon(self):
        self._beacon_seq += 1
        self.monc.send(MM.MMDSBeacon(
            name=self.name, addr=[self.addr.host, self.addr.port],
            state=self.state, seq=self._beacon_seq))

    def _beacon_loop(self):
        while self.running:
            self._send_beacon()
            with self.lock:
                if self.state == "active" and self.meta is not None \
                        and _now() - self._last_flush \
                        > self.flush_interval:
                    try:
                        self._flush(trim=True)
                    except Exception:   # noqa: BLE001 — cluster churn;
                        pass            # journal still has everything
                elif self.state != "active":
                    # a transiently failed activation retries while
                    # the map still names us active (pools may have
                    # been mid-create on the first attempt)
                    me = self.fsmap.mds_info.get(self.name)
                    if me is not None and me.state == STATE_ACTIVE:
                        try:
                            self._activate(me.fscid, me.rank)
                        except Exception:   # noqa: BLE001
                            pass
            time.sleep(self.beacon_interval)

    # -- fsmap consumption -------------------------------------------------
    def _on_fsmap(self, epoch: int, fsmap_dict: dict):
        with self.lock:
            self.fsmap = FSMap.from_dict(fsmap_dict)
            # subtree ownership is a pure function of max_mds: when it
            # changes, flush everything journaled to the dirfrags and
            # drop caches so the NEW owner of any re-homed subtree
            # reads current state from RADOS (the static-partition
            # stand-in for the reference Migrator's export flush)
            if self.state == "active" and self.meta is not None:
                fs = self.fsmap.filesystems.get(self.fscid)
                if fs is not None and \
                        fs.max_mds != getattr(self, "_last_max_mds",
                                              fs.max_mds):
                    try:
                        self._flush(trim=True)
                    except Exception:   # noqa: BLE001
                        pass
                    self._dirs.clear()
                    self._frags_cache.clear()
                    if getattr(self, "_inode_cache", None):
                        self._inode_cache.clear()
                    if self.rank == 0:
                        # a live shrink orphans demoted ranks'
                        # journals (their daemons may be dead):
                        # adopt them now, not only at activation
                        try:
                            self._replay_orphan_journals(fs.max_mds)
                        except Exception:   # noqa: BLE001
                            pass
                if fs is not None:
                    self._last_max_mds = fs.max_mds
            me = self.fsmap.mds_info.get(self.name)
            if me is not None and me.state == STATE_ACTIVE \
                    and self.state != "active":
                try:
                    self._activate(me.fscid, me.rank)
                except Exception:   # noqa: BLE001 — pools may still be
                    # creating; the next fsmap push (or beacon-driven
                    # re-promotion) retries
                    self.state = "standby"
            elif (me is None or me.state != STATE_ACTIVE) \
                    and self.state == "active":
                # mon failed us (partition zombie): drop rank, the
                # reference respawns — we fall back to standby
                self._deactivate()

    # -- activation / journal replay --------------------------------------
    def _activate(self, fscid: int, rank: int):
        fs = self.fsmap.filesystems[fscid]
        try:
            self.rados = Rados(self.monmap,
                               name=f"client.mds-{self.name}",
                               auth=self.auth).connect()
            self.meta = IoCtx(self.rados, fs.metadata_pool, "")
            self.data = IoCtx(self.rados, fs.data_pool, "")
            self.rank = rank
            self.fscid = fscid
            self._last_max_mds = fs.max_mds
            self._dirs.clear()
            self._frags_cache.clear()
            self._dirty_set.clear()
            self._dirty_rm.clear()
            self._completed.clear()
            self._replay_journal()
            if rank == 0:
                self._replay_orphan_journals(fs.max_mds)
            self._load_inotable()
        except Exception:
            if self.rados is not None:
                self.rados.shutdown()
                self.rados = None
            self.meta = self.data = None
            raise
        self.state = "active"
        self._last_flush = _now()
        self._send_beacon()

    def _deactivate(self):
        if self.meta is not None:
            try:
                # a demoted rank's journaled metadata must land in the
                # dirfrags — nobody replays a demoted rank's journal
                # while it stays within max_mds bounds
                self._flush(trim=True)
            except Exception:   # noqa: BLE001 — pools may be gone
                pass
        self.state = "standby"
        self.rank = -1
        self._dirs.clear()
        self._frags_cache.clear()
        self._dirty_set.clear()
        self._dirty_rm.clear()
        self.sessions.clear()
        if self.rados is not None:
            self.rados.shutdown()
            self.rados = None
        self.meta = self.data = None

    @property
    def _journal_oid(self) -> str:
        return JHEAD.format(rank=max(self.rank, 0))

    @property
    def _inotable_oid(self) -> str:
        return INOTABLE.format(rank=max(self.rank, 0))

    @property
    def _inodes_oid(self) -> str:
        return INODES

    # -- multi-link inode rows --------------------------------------------
    # (reference: a hard link makes the inode shared — the reference
    # keeps the inode on the primary dentry and "remote" dentries
    # reference it by ino; here shared inodes move into an inode-row
    # omap and every dentry becomes a remote stub)
    def _inode_row(self, ino: int) -> dict | None:
        cache = getattr(self, "_inode_cache", None)
        if cache is None:
            cache = self._inode_cache = {}
        if ino in cache:
            return cache[ino]
        try:
            raw = self.meta.omap_get(self._inodes_oid).get(str(ino))
        except ObjectNotFound:
            raw = None
        row = json.loads(raw.decode()) if raw else None
        cache[ino] = row
        return row

    def _inode_apply(self, sub: list):
        """'iset'/'irm' journal sub-ops write through (idempotent)."""
        cache = getattr(self, "_inode_cache", None)
        if cache is None:
            cache = self._inode_cache = {}
        if sub[0] == "iset":
            _, ino, row = sub
            cache[int(ino)] = row
            self.meta.omap_set(self._inodes_oid, {
                str(ino): json.dumps(row).encode()})
        elif sub[0] == "irm":
            _, ino = sub
            cache[int(ino)] = None
            try:
                self.meta.omap_rm_keys(self._inodes_oid, [str(ino)])
            except ObjectNotFound:
                pass

    def _resolve_rec(self, rec: dict) -> dict:
        """Overlay the shared inode row onto a remote dentry stub."""
        if rec and rec.get("remote"):
            row = self._inode_row(rec["ino"])
            if row:
                rec = dict(rec, **row)
        return rec

    def _replay_journal(self):
        """Apply every journaled event to the backing dirfrags, then
        trim (reference MDLog replay on rank takeover)."""
        self.perf.inc("replays")
        try:
            entries = self.meta.omap_get(self._journal_oid)
        except ObjectNotFound:
            self._jseq = self._jfirst = 1
            return
        seqs = sorted(int(k[1:]) for k in entries if k.startswith("e"))
        for seq in seqs:
            ev = json.loads(entries[f"e{seq:020d}"].decode())
            self._apply_event(ev)
            if ev.get("client") is not None:
                self._completed[(ev["client"], ev["tid"])] = \
                    ev.get("reply", {"rc": 0})
        self._jseq = (seqs[-1] + 1) if seqs else 1
        self._jfirst = seqs[0] if seqs else self._jseq
        self._flush(trim=True)

    def _replay_orphan_journals(self, max_mds: int):
        """Shrink with a dead rank: its journal would be orphaned
        (acked metadata lost).  Rank 0 adopts journals of every rank
        >= max_mds at activation — events are idempotent sub-op lists,
        so replay + trim is safe (reference: the stopping rank drains
        its own journal; a dead one is recovered the same way)."""
        for r in range(max_mds, 16):
            oid = JHEAD.format(rank=r)
            try:
                entries = self.meta.omap_get(oid)
            except ObjectNotFound:
                continue
            seqs = sorted(int(k[1:]) for k in entries
                          if k.startswith("e"))
            for seq in seqs:
                ev = json.loads(entries[f"e{seq:020d}"].decode())
                self._apply_event(ev)
                if ev.get("client") is not None:
                    self._completed[(ev["client"], ev["tid"])] = \
                        ev.get("reply", {"rc": 0})
            if seqs:
                self._flush()
                try:
                    self.meta.omap_rm_keys(
                        oid, [f"e{s:020d}" for s in seqs])
                except ObjectNotFound:
                    pass

    def _apply_event(self, ev: dict):
        """Events are lists of idempotent sub-ops, safe to re-apply."""
        for sub in ev["subs"]:
            kind = sub[0]
            if kind == "set":
                _, dino, name, rec = sub
                self._dir(dino)[name] = rec
                self._dirty_set.setdefault(dino, {})[name] = rec
                self._dirty_rm.get(dino, set()).discard(name)
            elif kind == "rm":
                _, dino, name = sub
                self._dir(dino).pop(name, None)
                self._dirty_rm.setdefault(dino, set()).add(name)
                self._dirty_set.get(dino, {}).pop(name, None)
            elif kind in ("iset", "irm"):
                self._inode_apply(sub)
            elif kind == "inotable":
                _, limit = sub
                cur = self._backing_inotable()
                if limit > cur:
                    self.meta.omap_set(self._inotable_oid,
                                       {"next": str(limit).encode()})
                self._ino_limit = max(self._ino_limit, limit)

    # -- inode table -------------------------------------------------------
    def _backing_inotable(self) -> int:
        try:
            kv = self.meta.omap_get(self._inotable_oid)
            return int(kv.get("next", b"0"))
        except ObjectNotFound:
            return 0

    def _load_inotable(self):
        # rank-disjoint inode number spaces (reference: per-rank
        # inotables partition a prealloc range): rank r allocates from
        # r << 40, so two ranks can never mint the same ino
        rank_base = (max(self.rank, 0) << 40) + ROOT_INO + 1
        base = max(self._backing_inotable(), rank_base,
                   self._ino_limit)
        self._next_ino = base
        self._ino_limit = base

    def _alloc_ino(self) -> tuple[int, list]:
        """→ (ino, extra journal sub-ops claiming a fresh chunk)."""
        subs = []
        if self._next_ino >= self._ino_limit:
            self._ino_limit = self._next_ino + INO_CHUNK
            subs.append(["inotable", self._ino_limit])
        ino = self._next_ino
        self._next_ino += 1
        return ino, subs

    # -- dirfrag cache -----------------------------------------------------
    def _nfrags(self, ino: int) -> int:
        """Fragment count from the directory's fragtree row (frag 0);
        1 ⇒ unfragmented."""
        n = self._frags_cache.get(ino)
        if n is None:
            try:
                row = self.meta.omap_get(
                    dirfrag_oid(ino), keys=[FRAGTREE_KEY]
                ).get(FRAGTREE_KEY)
                n = int(json.loads(bytes(row))["nfrags"]) if row else 1
            except ObjectNotFound:
                n = 1
            self._frags_cache[ino] = n
        return n

    def _dir(self, ino: int) -> dict[str, dict]:
        d = self._dirs.get(ino)
        if d is None:
            d = self._read_dir_backing(ino)
            self._dirs[ino] = d
        return d

    def _read_dir_backing(self, ino: int) -> dict[str, dict]:
        """Uncached merged view of every fragment.  A row found in a
        fragment its hash no longer points at is an interrupted
        split's leftover: the correctly-placed copy wins the merge and
        the stale one is removed on the spot (self-healing — without
        this, a later unlink would only reach the new home and the
        stale copy would resurrect on the next cache drop)."""
        # fragment 0 carries the fragtree row: one read serves both
        # the count and the rows (the split-count probe and the data
        # read used to be two round trips)
        try:
            raw0 = self.meta.omap_get(dirfrag_oid(ino, 0))
        except ObjectNotFound:
            raw0 = {}
        ft = raw0.get(FRAGTREE_KEY)
        nf = int(json.loads(bytes(ft))["nfrags"]) if ft else 1
        self._frags_cache[ino] = nf
        d: dict[str, dict] = {}
        stale: dict[int, list[str]] = {}

        def absorb(f: int, raw: dict):
            for k, v in raw.items():
                if k == FRAGTREE_KEY:
                    continue
                if frag_of(k, nf) != f:
                    # never authoritative: the split wrote the new
                    # home BEFORE bumping the fragtree, so a live row
                    # always has a correctly-placed copy
                    stale.setdefault(f, []).append(k)
                    continue
                d[k] = json.loads(v.decode())

        absorb(0, raw0)
        for f in range(1, nf):
            try:
                absorb(f, self.meta.omap_get(dirfrag_oid(ino, f)))
            except ObjectNotFound:
                continue
        # NB: rows a pre-bump-interrupted split left in [nf, 2nf) are
        # NOT probed here — they are invisible to reads (loops stop at
        # nf) and _maybe_split sanitizes its target fragments before
        # merging, so they can never resurrect.  Probing them from a
        # reader would both cost an extra round trip per load and race
        # the OWNER rank's in-flight split (sweeping rows it just
        # wrote, before the bump makes them authoritative).
        for f, names in stale.items():
            try:
                self.meta.omap_rm_keys(dirfrag_oid(ino, f),
                                       sorted(names))
            except Exception:   # noqa: BLE001 — healing is best-effort
                pass
        return d

    def _journal(self, subs: list, client=None, tid=None, reply=None):
        ev = {"subs": subs, "client": client, "tid": tid}
        if reply is not None:
            ev["reply"] = reply
        self.perf.inc("journal_events")
        seq = self._jseq
        self._jseq += 1
        self.meta.omap_set(self._journal_oid,
                           {f"e{seq:020d}": json.dumps(ev).encode()})
        for sub in subs:
            if sub[0] == "inotable":
                # table claims apply to backing immediately — a chunk
                # must never be re-handed after replay
                cur = self._backing_inotable()
                if sub[1] > cur:
                    self.meta.omap_set(self._inotable_oid,
                                       {"next": str(sub[1]).encode()})

    def _flush(self, trim: bool = False):
        """Write dirty dirfrag deltas to their fragment objects (each
        dentry routed by hash); optionally trim the journal entries
        they cover (reference MDLog trim).  Over-size directories
        split afterwards."""
        upto = self._jseq
        touched = set()
        for dino, sets in list(self._dirty_set.items()):
            if sets:
                nf = self._nfrags(dino)
                per: dict[int, dict] = {}
                for n, r in sets.items():
                    per.setdefault(frag_of(n, nf), {})[n] = \
                        json.dumps(r).encode()
                for f, rows in per.items():
                    self.meta.omap_set(dirfrag_oid(dino, f), rows)
                touched.add(dino)
            self._dirty_set.pop(dino, None)
        for dino, rms in list(self._dirty_rm.items()):
            if rms:
                nf = self._nfrags(dino)
                per_rm: dict[int, list] = {}
                for n in rms:
                    per_rm.setdefault(frag_of(n, nf), []).append(n)
                for f, names in per_rm.items():
                    try:
                        self.meta.omap_rm_keys(dirfrag_oid(dino, f),
                                               sorted(names))
                    except ObjectNotFound:
                        pass
            self._dirty_rm.pop(dino, None)
        for dino in touched:
            self._maybe_split(dino)
        if trim and upto > self._jfirst:
            keys = [f"e{s:020d}" for s in range(self._jfirst, upto)]
            try:
                self.meta.omap_rm_keys(self._journal_oid, keys)
            except ObjectNotFound:
                pass
            self._jfirst = upto
        self._last_flush = _now()

    def _maybe_split(self, dino: int):
        """Double the fragment count when a directory outgrows the
        split size (reference MDBalancer/CDir::split).  Redistribution
        is local by construction: a dentry in frag f moves to f or
        f + old_n (exactly, for power-of-two counts).  Crash safety:
        (1) write moved rows to their NEW fragments, (2) bump the
        fragtree, (3) remove the old copies — an interruption leaves
        at worst a row duplicated in its old fragment, which
        _read_dir_backing detects by re-hashing and lazily removes."""
        old_n = self._nfrags(dino)
        d = self._dir(dino)
        if old_n >= DIRFRAG_MAX or \
                len(d) <= self.dirfrag_split_size * old_n:
            return
        new_n = old_n * 2
        per: dict[int, dict[str, bytes]] = {}
        for name, rec in d.items():
            per.setdefault(frag_of(name, new_n), {})[name] = \
                json.dumps(rec).encode()
        # (1) the moved rows land in their new homes first — after
        # dropping any leftovers a previously-interrupted split left
        # there (omap_set merges; a stale row would otherwise ride
        # into the new fragment as a resurrected dentry)
        for f in range(old_n, new_n):
            try:
                existing = set(self.meta.omap_get(
                    dirfrag_oid(dino, f)))
            except ObjectNotFound:
                existing = set()
            dead = sorted(existing - set(per.get(f, {}))
                          - {FRAGTREE_KEY})
            if dead:
                self.meta.omap_rm_keys(dirfrag_oid(dino, f), dead)
            if per.get(f):
                self.meta.omap_set(dirfrag_oid(dino, f), per[f])
        # (2) only now does the fragtree say the split happened
        self.meta.omap_set(dirfrag_oid(dino, 0), {
            FRAGTREE_KEY: json.dumps({"nfrags": new_n}).encode()})
        self._frags_cache[dino] = new_n
        # (3) drop the moved rows from their old fragments
        for f in range(old_n):
            dead = sorted(per.get(f + old_n, {}))
            if dead:
                try:
                    self.meta.omap_rm_keys(dirfrag_oid(dino, f), dead)
                except ObjectNotFound:
                    pass

    def _remove_dir_backing(self, ino: int):
        """Remove every fragment object of a (now empty) directory."""
        for f in range(max(self._nfrags(ino), 1)):
            try:
                self.meta.remove(dirfrag_oid(ino, f))
            except ObjectNotFound:
                pass
        self._frags_cache.pop(ino, None)

    # -- dispatch ----------------------------------------------------------
    def ms_dispatch(self, msg) -> bool:
        if isinstance(msg, M.MClientSession):
            with self.lock:
                if msg.op == "request_open":
                    self.sessions[msg.client] = msg.seq or 0
                    op = "open"
                else:
                    self.sessions.pop(msg.client, None)
                    op = "close"
            try:
                msg.connection.send_message(M.MClientSession(
                    op=op, client=msg.client, seq=msg.seq))
            except ConnectionError:
                pass
            return True
        if isinstance(msg, M.MClientRequest):
            with self.lock:
                self.perf.inc("request")
                rc, outs, result = self._handle_request(msg)
            try:
                msg.connection.send_message(M.MClientReply(
                    tid=msg.tid, rc=rc, outs=outs, result=result))
                self.perf.inc("reply")
            except ConnectionError:
                pass
            return True
        return False

    def _handle_request(self, msg) -> tuple[int, str, object]:
        if self.state != "active":
            return -108, "mds not active", None      # ESHUTDOWN analog
        key = (msg.client, msg.tid)
        if key in self._completed:
            done = self._completed[key]
            return done.get("rc", 0), "", done.get("result")
        args = msg.args or {}
        # dentry-name hygiene, enforced once for every op: NUL is the
        # fragtree row's namespace (FRAGTREE_KEY) and '/' would break
        # path resolution — both are illegal in POSIX names anyway.
        # ""/"."/".." are refused only for mutations: the read path
        # deliberately uses name="" for the root lookup
        mutating = msg.op not in ("lookup", "getattr", "readdir")
        for k in ("name", "sname", "dname"):
            n = args.get(k)
            if not isinstance(n, str):
                continue
            if "\x00" in n or "/" in n or \
                    (mutating and n in ("", ".", "..")):
                return -22, f"invalid dentry name {n!r}", None
        handler = getattr(self, f"_op_{msg.op}", None)
        if handler is None:
            return -22, f"unknown mds op {msg.op!r}", None
        try:
            rc, outs, result = handler(args, msg.client, msg.tid)
        except ObjectNotFound:
            return -2, "metadata object vanished", None
        except Exception as e:      # noqa: BLE001 — a RADOS error
            # (pool churn, mon timeout) must become a reply, not a
            # swallowed dispatcher exception the client times out on
            return -5, f"mds op {msg.op!r} failed: {e!r}", None
        return rc, outs, result

    # -- read ops ----------------------------------------------------------
    @staticmethod
    def _root_rec() -> dict:
        return {"ino": ROOT_INO, "type": "dir", "size": 0, "mtime": 0}

    def _op_lookup(self, args, client, tid):
        dino, name = args["dir"], args["name"]
        if dino == ROOT_INO and name == "":
            return 0, "", self._root_rec()
        rec = self._dir(dino).get(name)
        if rec is None:
            return -2, f"no dentry {name!r}", None
        return 0, "", self._resolve_rec(rec)

    _op_getattr = _op_lookup

    def _op_readdir(self, args, client, tid):
        d = self._dir(args["dir"])
        return 0, "", sorted([name, self._resolve_rec(rec)]
                             for name, rec in d.items())

    # -- mutations (journaled, deduped) ------------------------------------
    def _mutate(self, subs, client, tid, result=None):
        self._journal(subs, client=client, tid=tid,
                      reply={"rc": 0, "result": result})
        for s in [s for s in subs if s[0] != "inotable"]:
            self._apply_cache(s)
        self._completed[(client, tid)] = {"rc": 0, "result": result}
        return 0, "", result

    def _apply_cache(self, sub):
        if sub[0] == "set":
            _, dino, name, rec = sub
            self._dir(dino)[name] = rec
            self._dirty_set.setdefault(dino, {})[name] = rec
            self._dirty_rm.get(dino, set()).discard(name)
        elif sub[0] == "rm":
            _, dino, name = sub
            self._dir(dino).pop(name, None)
            self._dirty_rm.setdefault(dino, set()).add(name)
            self._dirty_set.get(dino, {}).pop(name, None)
        elif sub[0] in ("iset", "irm"):
            self._inode_apply(sub)

    def _op_mkdir(self, args, client, tid):
        dino, name = args["dir"], args["name"]
        if name in self._dir(dino):
            return -17, f"{name!r} exists", None
        ino, extra = self._alloc_ino()
        rec = {"ino": ino, "type": "dir", "size": 0, "mtime": _now()}
        return self._mutate(extra + [["set", dino, name, rec]],
                            client, tid, rec)

    def _op_create(self, args, client, tid):
        dino, name = args["dir"], args["name"]
        existing = self._dir(dino).get(name)
        if existing is not None:
            if args.get("excl"):
                # O_CREAT|O_EXCL: EEXIST whatever the dentry is —
                # including a (possibly dangling) symlink (POSIX)
                return -17, f"{name!r} exists", None
            if existing["type"] != "file":
                return -21, f"{name!r} is a directory", None
            return 0, "", self._resolve_rec(existing)
        ino, extra = self._alloc_ino()
        rec = {"ino": ino, "type": "file", "size": 0, "mtime": _now()}
        if args.get("layout"):
            rec["layout"] = args["layout"]
        return self._mutate(extra + [["set", dino, name, rec]],
                            client, tid, rec)

    def _op_setattr(self, args, client, tid):
        dino, name = args["dir"], args["name"]
        rec = self._dir(dino).get(name)
        if rec is None:
            return -2, f"no dentry {name!r}", None
        if rec.get("remote"):
            # shared inode: attrs live on the inode row so every
            # link sees them (reference: inode, not dentry, state)
            row = dict(self._inode_row(rec["ino"]) or {})
            for fld in ("size", "mtime"):
                if args.get(fld) is not None:
                    row[fld] = args[fld]
            rc = self._mutate([["iset", rec["ino"], row]], client,
                              tid, dict(rec, **row))
            return rc
        rec = dict(rec)
        for fld in ("size", "mtime"):
            if args.get(fld) is not None:
                rec[fld] = args[fld]
        return self._mutate([["set", dino, name, rec]], client, tid, rec)

    def _op_unlink(self, args, client, tid):
        dino, name = args["dir"], args["name"]
        rec = self._dir(dino).get(name)
        if rec is None:
            return -2, f"no dentry {name!r}", None
        if rec["type"] == "dir":
            return -21, f"{name!r} is a directory", None
        if rec.get("remote"):
            subs, purge_rec = self._drop_remote_link(rec)
            rc = self._mutate([["rm", dino, name]] + subs, client, tid)
            if purge_rec is not None:
                self._purge_file(purge_rec)
            return rc
        rc = self._mutate([["rm", dino, name]], client, tid)
        if rec["type"] == "file":
            self._purge_file(rec)
        return rc

    def _drop_remote_link(self, rec: dict):
        """One link to a shared inode goes away: → (journal subs,
        purge_rec-or-None) — shared by unlink and rename-overwrite so
        the nlink bookkeeping cannot diverge between them."""
        row = dict(self._inode_row(rec["ino"]) or {"nlink": 1})
        nlink = int(row.get("nlink", 1)) - 1
        if nlink > 0:
            row["nlink"] = nlink
            return [["iset", rec["ino"], row]], None
        return [["irm", rec["ino"]]], dict(rec, **row)

    def _op_link(self, args, client, tid):
        """Hard link: args {tdir, tname} (existing file) + {dir, name}
        (new dentry).  Both dentries become remote stubs over a shared
        inode row (reference: primary + remote dentry on one inode)."""
        tdino, tname = args["tdir"], args["tname"]
        dino, name = args["dir"], args["name"]
        target = self._dir(tdino).get(tname)
        if target is None:
            return -2, f"no dentry {tname!r}", None
        if target["type"] != "file":
            return -1, "hard links to non-files are not allowed", None
        if name in self._dir(dino):
            return -17, f"{name!r} exists", None
        subs = []
        if target.get("remote"):
            row = dict(self._inode_row(target["ino"]) or {"nlink": 1})
            row["nlink"] = int(row.get("nlink", 1)) + 1
            stub = dict(target)
        else:
            row = {"size": target.get("size", 0),
                   "mtime": target.get("mtime", 0), "nlink": 2}
            stub = {k: v for k, v in target.items()
                    if k not in ("size", "mtime")}
            stub["remote"] = True
            subs.append(["set", tdino, tname, stub])
        subs.append(["iset", target["ino"], row])
        subs.append(["set", dino, name, stub])
        return self._mutate(subs, client, tid,
                            self._resolve_rec(stub))

    def _op_symlink(self, args, client, tid):
        dino, name = args["dir"], args["name"]
        if name in self._dir(dino):
            return -17, f"{name!r} exists", None
        ino, extra = self._alloc_ino()
        rec = {"ino": ino, "type": "symlink",
               "target": str(args["target"]), "size": 0,
               "mtime": _now()}
        return self._mutate(extra + [["set", dino, name, rec]],
                            client, tid, rec)

    # -- snapshots (.snap; reference SnapServer + snaprealms) --------------
    # The data plane rides pool snapshots (OSD-side COW clones, exactly
    # the reference's SnapContext machinery); the metadata plane is an
    # eager copy of the subtree's RESOLVED dentry tables into manifest
    # objects (the reference COWs dirfrags lazily per snapid — same
    # observable behavior, simpler recovery story).
    def _data_pool_ioctx(self):
        from ..osdc.librados import IoCtx
        pid = self.data.pool_id
        pname = self.data.objecter.osdmap.pools[pid].name
        return IoCtx(self.rados, pid, pname)

    def _snap_registry(self) -> dict[str, dict]:
        try:
            rows = self.meta.omap_get(SNAPS_OID)
        except ObjectNotFound:
            return {}
        return {k: json.loads(bytes(v)) for k, v in rows.items()}

    def _op_mksnap(self, args, client, tid):
        from ..osdc.librados import Error as RadosError
        dino, name = args["dir"], args["name"]
        if dino == ROOT_INO and \
                getattr(self, "_last_max_mds", 1) > 1:
            # "/" spans subtree ranks; freezing it would need a
            # cross-rank journal flush (reference: snap realms span
            # ranks via the SnapServer's global table) — refuse
            # loudly rather than snapshot other ranks' unflushed state
            return (-22, "snapshot of / needs max_mds=1 "
                         "(subtrees span ranks)", None)
        key = f"{dino:x}\x00{name}"
        if key in self._snap_registry():
            return -17, f"snapshot {name!r} exists", None
        # journaled-but-unflushed metadata must reach the backing
        # store first: the manifest copy below reads the dirfrags
        # (this op was routed to the subtree's OWNER rank, so our
        # journal is the only one covering it)
        self._flush(trim=True)
        psnap = f"cfs-{dino:x}-{name}"
        ioctx = self._data_pool_ioctx()
        try:
            ioctx.create_snap(psnap)
        except RadosError:
            # a crash between pool-snap creation and the registry
            # write left this pool snap behind: adopt it instead of
            # poisoning the name forever
            pass
        snapid = ioctx.snap_lookup(psnap)
        stack = [dino]
        while stack:
            d = stack.pop()
            rows = {}
            for n, rec in self._dir(d).items():
                rec = self._resolve_rec(rec)
                rows[n] = json.dumps(rec).encode()
                if rec["type"] == "dir":
                    stack.append(rec["ino"])
            if rows:
                self.meta.omap_set(snap_manifest_oid(snapid, d), rows)
        info = {"snapid": snapid, "pool_snap": psnap,
                "created": _now()}
        self.meta.omap_set(SNAPS_OID, {
            key: json.dumps(info).encode()})
        result = dict(info, name=name)
        # journal the completion so a client RESEND (lost reply,
        # failover) replays the original answer instead of -17
        self._journal([], client=client, tid=tid,
                      reply={"rc": 0, "result": result})
        self._completed[(client, tid)] = {"rc": 0, "result": result}
        return 0, "", result

    def _op_rmsnap(self, args, client, tid):
        dino, name = args["dir"], args["name"]
        key = f"{dino:x}\x00{name}"
        info = self._snap_registry().get(key)
        if info is None:
            return -2, f"no snapshot {name!r}", None
        snapid = info["snapid"]
        # drop the manifests by walking the frozen tree itself
        stack = [dino]
        while stack:
            d = stack.pop()
            try:
                rows = self.meta.omap_get(snap_manifest_oid(snapid, d))
            except ObjectNotFound:
                continue
            for v in rows.values():
                rec = json.loads(bytes(v))
                if rec.get("type") == "dir":
                    stack.append(rec["ino"])
            try:
                self.meta.remove(snap_manifest_oid(snapid, d))
            except ObjectNotFound:
                pass
        try:
            self._data_pool_ioctx().remove_snap(info["pool_snap"])
        except Exception:   # noqa: BLE001 — pool snap may be gone
            pass
        self.meta.omap_rm_keys(SNAPS_OID, [key])
        self._journal([], client=client, tid=tid,
                      reply={"rc": 0, "result": None})
        self._completed[(client, tid)] = {"rc": 0, "result": None}
        return 0, "", None

    def _op_lssnap(self, args, client, tid):
        dino = args["dir"]
        pre = f"{dino:x}\x00"
        out = [dict(info, name=k[len(pre):])
               for k, info in self._snap_registry().items()
               if k.startswith(pre)]
        return 0, "", sorted(out, key=lambda s: s["snapid"])

    def _op_snapinfo(self, args, client, tid):
        key = f"{args['dir']:x}\x00{args['snap']}"
        info = self._snap_registry().get(key)
        if info is None:
            return -2, f"no snapshot {args['snap']!r}", None
        return 0, "", dict(info, name=args["snap"])

    def _op_snap_readdir(self, args, client, tid):
        try:
            rows = self.meta.omap_get(
                snap_manifest_oid(args["snapid"], args["dir"]))
        except ObjectNotFound:
            rows = {}
        return 0, "", sorted(
            [n, json.loads(bytes(v))] for n, v in rows.items())

    def _op_snap_lookup(self, args, client, tid):
        try:
            rows = self.meta.omap_get(
                snap_manifest_oid(args["snapid"], args["dir"]),
                keys=[args["name"]])
        except ObjectNotFound:
            rows = {}
        row = rows.get(args["name"])
        if row is None:
            return -2, f"no snapped dentry {args['name']!r}", None
        return 0, "", json.loads(bytes(row))

    def _subtree_owner(self, top_name: str) -> int:
        """The rank owning a top-level directory's subtree (the
        static partition rule clients route by)."""
        import zlib
        fs = self.fsmap.filesystems.get(self.fscid)
        n = max(1, fs.max_mds) if fs is not None else 1
        return zlib.crc32(top_name.encode()) % n

    def _op_rmdir(self, args, client, tid):
        dino, name = args["dir"], args["name"]
        rec = self._dir(dino).get(name)
        if rec is None:
            return -2, f"no dentry {name!r}", None
        if rec["type"] != "dir":
            return -20, f"{name!r} is not a directory", None
        if dino == ROOT_INO and \
                self._subtree_owner(name) != self.rank:
            # the dir's CONTENTS are another rank's subtree: check
            # emptiness on a FRESH uncached read (our cached copy can
            # be stale and must never stick — the owner's unflushed
            # journal window remains the slice's known gap vs the
            # reference's cross-MDS slave requests)
            self._dirs.pop(rec["ino"], None)
            self._frags_cache.pop(rec["ino"], None)
            if self._read_dir_backing(rec["ino"]):
                return -39, f"{name!r} not empty", None
        elif self._dir(rec["ino"]):
            return -39, f"{name!r} not empty", None
        rc = self._mutate([["rm", dino, name]], client, tid)
        self._remove_dir_backing(rec["ino"])
        self._dirs.pop(rec["ino"], None)
        return rc

    def _descends_from(self, root_ino: int, needle: int) -> bool:
        """True if `needle` is `root_ino` or inside its subtree."""
        stack = [root_ino]
        while stack:
            ino = stack.pop()
            if ino == needle:
                return True
            stack.extend(r["ino"] for r in self._dir(ino).values()
                         if r["type"] == "dir")
        return False

    def _op_rename(self, args, client, tid):
        sdino, sname = args["sdir"], args["sname"]
        ddino, dname = args["ddir"], args["dname"]
        rec = self._dir(sdino).get(sname)
        if rec is None:
            return -2, f"no dentry {sname!r}", None
        if rec["type"] == "dir" and \
                self._descends_from(rec["ino"], ddino):
            # POSIX EINVAL: a directory cannot move into its own
            # subtree (the detached cycle would orphan it)
            return -22, f"{sname!r} is an ancestor of the target", None
        target = self._dir(ddino).get(dname)
        purge = None
        if target is not None and \
                not (sdino == ddino and sname == dname):
            if target["type"] == "dir":
                if target["ino"] == rec["ino"]:
                    return 0, "", rec       # rename onto itself
                if rec["type"] != "dir":
                    return -21, f"{dname!r} is a directory", None
                if self._dir(target["ino"]):
                    return -39, f"{dname!r} not empty", None
            elif rec["type"] == "dir":
                return -20, f"{dname!r} is not a directory", None
            else:
                purge = target
        subs = [["rm", sdino, sname], ["set", ddino, dname, rec]]
        purge_rec = None
        if purge is not None:
            if purge.get("remote"):
                extra, purge_rec = self._drop_remote_link(purge)
                subs.extend(extra)
            elif purge["type"] == "file":
                purge_rec = purge
        rc = self._mutate(subs, client, tid, rec)
        if purge_rec is not None:
            self._purge_file(purge_rec)
        return rc

    def _purge_file(self, rec: dict):
        """Delete a dead file's data objects (reference purge queue —
        synchronous here; the namespace op already committed)."""
        from ..osdc.striper import FileLayout
        layout = rec.get("layout") or {}
        osize = layout.get("object_size", FileLayout.object_size)
        nobj = max(1, -(-int(rec.get("size", 0)) // osize))
        for objno in range(nobj):
            try:
                self.data.remove(data_oid(rec["ino"], objno))
            except ObjectNotFound:
                pass

"""CephFS metadata layer (reference ``src/mds/`` — SURVEY.md §3.9):
the MDS daemon serves a POSIX namespace whose metadata lives in RADOS
omap dirfrags with a write-ahead journal, while file DATA flows
client→OSD directly through the striper — the MDS is never on the
data path, exactly the reference's split."""

from .fsmap import FSMap, MDSInfo  # noqa: F401

"""Client ↔ MDS message types (reference ``src/messages/
MClientRequest.h`` / ``MClientReply.h`` / ``MClientSession.h`` —
SURVEY.md §3.2/§3.9).  JSON-in-frame like the mon plane: metadata RPC
is evolvability-bound, not byte-bound; the data plane never touches
the MDS."""

from __future__ import annotations

import json

from ..msg.message import Message, register_message


class _JsonMessage(Message):
    FIELDS: tuple = ()

    def __init__(self, **kw):
        super().__init__()
        for f in self.FIELDS:
            setattr(self, f, kw.get(f))

    def encode_payload(self, enc):
        enc.string(json.dumps({f: getattr(self, f) for f in self.FIELDS}))

    def decode_payload(self, dec, version):
        data = json.loads(dec.string())
        for f in self.FIELDS:
            setattr(self, f, data.get(f))


@register_message
class MClientSession(_JsonMessage):
    """Session open/close handshake (reference MClientSession).
    op: "request_open" / "request_close" from the client,
    "open" / "close" from the MDS."""
    TYPE = 60
    FIELDS = ("op", "client", "seq")


@register_message
class MClientRequest(_JsonMessage):
    """One metadata op.  `op` names the call (mkdir/create/lookup/
    readdir/unlink/rmdir/rename/setattr/getattr), `args` its operands
    (parent ino + dentry name addressing, like the reference's
    filepath-relative ops)."""
    TYPE = 61
    FIELDS = ("tid", "client", "op", "args")


@register_message
class MClientReply(_JsonMessage):
    TYPE = 62
    FIELDS = ("tid", "rc", "outs", "result")

"""FSMap — filesystem + MDS cluster state held by the monitors.

Reference behavior re-created (``src/mds/FSMap.h``, ``MDSMap.h``;
SURVEY.md §3.4/§3.9): an epoch-versioned map of filesystems (each
binding a metadata pool and a data pool) and of MDS daemons with their
rank/state (``up:active`` / ``up:standby``).  The mon's MDSMonitor
mutates it through Paxos; MDS daemons and clients subscribe to it the
way they subscribe to the OSDMap — clients find the active MDS's
address here, and a beacon timeout triggers the standby promotion that
drives failover.

Multi-rank (``max_mds`` > 1): the namespace is partitioned by
TOP-LEVEL directory — rank = crc32(top-level name) % max_mds, rank 0
owning the root itself (a static form of the reference's subtree
delegation, ``src/mds/Migrator.cc``; dynamic load-driven migration is
out of scope).  Clients route each metadata op to its subtree's rank;
each rank journals its own subtree (per-rank journal/inotable
objects) and allocates inodes from a rank-disjoint number space.
"""

from __future__ import annotations

from dataclasses import dataclass, field

STATE_STANDBY = "up:standby"
STATE_ACTIVE = "up:active"


@dataclass
class MDSInfo:
    """One registered MDS daemon (reference ``MDSMap::mds_info_t``)."""
    name: str
    addr: list          # [host, port] of its client-facing messenger
    state: str = STATE_STANDBY
    rank: int = -1      # -1 = no rank (standby)
    fscid: int = -1     # filesystem it is active for (-1 = none)

    def to_dict(self) -> dict:
        return {"name": self.name, "addr": list(self.addr),
                "state": self.state, "rank": self.rank,
                "fscid": self.fscid}

    @classmethod
    def from_dict(cls, d: dict) -> "MDSInfo":
        return cls(name=d["name"], addr=list(d["addr"]),
                   state=d["state"], rank=d["rank"], fscid=d["fscid"])


@dataclass
class Filesystem:
    """One filesystem (reference ``Filesystem`` in FSMap.h)."""
    fscid: int
    name: str
    metadata_pool: int
    data_pool: int
    max_mds: int = 1

    def to_dict(self) -> dict:
        return {"fscid": self.fscid, "name": self.name,
                "metadata_pool": self.metadata_pool,
                "data_pool": self.data_pool, "max_mds": self.max_mds}

    @classmethod
    def from_dict(cls, d: dict) -> "Filesystem":
        return cls(fscid=d["fscid"], name=d["name"],
                   metadata_pool=d["metadata_pool"],
                   data_pool=d["data_pool"],
                   max_mds=d.get("max_mds", 1))


@dataclass
class FSMap:
    epoch: int = 0
    next_fscid: int = 1
    filesystems: dict[int, Filesystem] = field(default_factory=dict)
    mds_info: dict[str, MDSInfo] = field(default_factory=dict)

    # -- queries -----------------------------------------------------------
    def fs_by_name(self, name: str) -> Filesystem | None:
        for fs in self.filesystems.values():
            if fs.name == name:
                return fs
        return None

    def active_for(self, fscid: int, rank: int = 0) -> MDSInfo | None:
        """The active MDS holding `rank` of a filesystem, if any."""
        for info in self.mds_info.values():
            if info.fscid == fscid and info.rank == rank \
                    and info.state == STATE_ACTIVE:
                return info
        return None

    def actives_for(self, fscid: int) -> dict[int, MDSInfo]:
        """rank → active MDS for a filesystem."""
        return {i.rank: i for i in self.mds_info.values()
                if i.fscid == fscid and i.state == STATE_ACTIVE}

    def standbys(self) -> list[MDSInfo]:
        return [i for i in self.mds_info.values()
                if i.state == STATE_STANDBY]

    # -- codec -------------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "next_fscid": self.next_fscid,
            "filesystems": {str(c): fs.to_dict()
                            for c, fs in self.filesystems.items()},
            "mds_info": {n: i.to_dict()
                         for n, i in self.mds_info.items()},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FSMap":
        return cls(
            epoch=d["epoch"],
            next_fscid=d.get("next_fscid", 1),
            filesystems={int(c): Filesystem.from_dict(fd)
                         for c, fd in d.get("filesystems", {}).items()},
            mds_info={n: MDSInfo.from_dict(i)
                      for n, i in d.get("mds_info", {}).items()},
        )

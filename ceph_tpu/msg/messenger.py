"""The messenger: asyncio frames, handshake, auth, resume, injection.

Reference behavior re-created (``src/msg/async/AsyncMessenger.cc``,
``ProtocolV2.{h,cc}``, ``frames_v2``; SURVEY.md §3.2):

- banner + hello exchange (entity name, address, features, mode) on
  connect;
- optional CephX-style authorizer check during the handshake
  (``core.auth``): the accepting side verifies the ticket, both sides
  then share a session key;
- connection modes, negotiated in the handshake and required to match
  (the reference's ``ms_mode`` crc/secure pair,
  ``ProtocolV2.cc``):
  * **crc**: frames ``u32 len | u8 tag | u32 crc | payload [| 8B
    sig]`` — integrity only; with a session key each frame is also
    HMAC-signed;
  * **secure**: post-handshake frames are AES-128-GCM encrypted with
    the session key (nonce ‖ ciphertext ‖ gcm-tag, AAD = frame tag),
    crc over the ciphertext; confidentiality AND tamper rejection —
    a flipped bit fails the GCM tag and faults the transport.  Secure
    mode refuses to come up without an authenticated session key.
- per-connection ordered delivery with sequence numbers, acks, replay
  of unacked messages after reconnect, and receive-side dedup — the
  msgr2 session-resume contract;
- ``ms_inject_socket_failures``: randomly cut the socket every ~1/N
  sends (the reference's fault-injection knob, used by the tests).

Public API mirrors the reference: ``Messenger(entity)``, ``bind()``,
``add_dispatcher()``, ``connect_to(addr)`` → ``Connection`` with
``send_message(msg)``; dispatch callbacks run on the messenger thread.
"""

from __future__ import annotations

import asyncio
import json
import os
import struct
import threading
import zlib
from dataclasses import dataclass

from ..core.auth import AuthError, CryptoKey, ServiceVerifier
from ..core.encoding import DecodeError
from .fault import DELAY, DROP, DUP, PARTITION, REORDER, FaultInjector
from .message import Message

BANNER = b"ceph-tpu msgr2\n"

TAG_HELLO = 1
TAG_AUTH = 2
TAG_AUTH_REPLY = 3
TAG_MSG = 4
TAG_ACK = 5
TAG_KEEPALIVE = 6
TAG_RESET = 7


@dataclass(frozen=True)
class EntityAddr:
    host: str
    port: int
    nonce: int = 0

    def __str__(self):
        return f"{self.host}:{self.port}/{self.nonce}"


# everything a failed/garbled handshake can throw; retrying is right for
# each (a peer mid-restart can emit any of them)
_HANDSHAKE_ERRORS = (ConnectionError, OSError, EOFError, ValueError,
                     KeyError, struct.error)


async def _read_json(r: asyncio.StreamReader) -> dict:
    """One length-prefixed JSON handshake blob."""
    (n,) = struct.unpack("<I", await r.readexactly(4))
    if n > 1 << 20:
        raise ConnectionError("handshake blob too large")
    return json.loads((await r.readexactly(n)).decode())


class Dispatcher:
    """Reference Dispatcher: subclass and register via
    add_dispatcher(); first dispatcher returning True consumes."""

    def ms_dispatch(self, msg: Message) -> bool:  # noqa: ARG002
        return False

    def ms_handle_reset(self, con: "Connection"):
        pass

    def ms_handle_accept(self, con: "Connection"):
        pass


class Connection:
    """One peer session (survives socket reconnects)."""

    def __init__(self, msgr: "Messenger", peer_addr: EntityAddr | None,
                 outgoing: bool):
        self.msgr = msgr
        self.peer_addr = peer_addr
        self.peer_name: str | None = None
        self.peer_nonce: int | None = None  # peer process incarnation
        self.outgoing = outgoing
        self.session_key: CryptoKey | None = None
        self.secure = False          # negotiated AES-GCM frame mode
        self.out_seq = 0
        self.in_seq = 0
        self._unacked: dict[int, Message] = {}
        self._send_q: asyncio.Queue = asyncio.Queue()
        self._writer: asyncio.StreamWriter | None = None
        self._closed = False
        self._tasks: list[asyncio.Task] = []
        self._reconnect_task: asyncio.Task | None = None  # strong ref:
        # asyncio keeps only weak task refs; an unreferenced reconnect
        # task gets garbage-collected MID-HANDSHAKE (GeneratorExit)
        self._gen = 0     # transport incarnation; stale-failure guard

    # -- public (thread-safe) ---------------------------------------------
    def send_message(self, msg: Message):
        if self._closed:
            raise ConnectionError("connection closed")
        if self._send_q.qsize() >= self.msgr.max_queued:
            # a dead peer must not grow an unbounded backlog; senders
            # (heartbeats, elections) retry at the protocol level
            raise ConnectionError("send queue full (peer unreachable?)")
        tracer = self.msgr.tracer
        span = None
        if tracer is not None and tracer.enabled:
            ctx = getattr(msg, "trace", None)
            if ctx:
                span = tracer.start_span(
                    f"wire_send:{type(msg).__name__}", parent=ctx,
                    tags={"layer": "wire",
                          "peer": self.peer_name or (
                              f"{self.peer_addr.host}:"
                              f"{self.peer_addr.port}"
                              if self.peer_addr else "?")})
        faults = self.msgr.faults
        if faults.active:
            dst = self.peer_name or (
                f"{self.peer_addr.host}:{self.peer_addr.port}"
                if self.peer_addr else "?")
            d = faults.decide(self.msgr.entity_name, dst)
            if span is not None and d.verdict is not None:
                span.set_tag("fault", d.verdict)
            if d.verdict in (DROP, PARTITION):
                if span is not None:
                    span.finish()
                return           # lost on the wire; protocols retry
            if d.verdict in (DELAY, REORDER):
                # late enqueue: anything sent inside the hold window
                # overtakes this message (seq is assigned at dequeue,
                # so the scramble is a real logical-order inversion)
                if span is not None:
                    span.set_tag("hold_s", round(d.hold_s, 6))
                    span.finish()
                self.msgr._call_soon(
                    self.msgr._loop.call_later, d.hold_s,
                    self._send_q.put_nowait, msg)
                return
            if d.verdict == DUP:
                # enqueue twice: the second pass gets a fresh seq, so
                # the session-layer dedup does NOT absorb it and the
                # application sees a true duplicate delivery
                self.msgr._call_soon(self._send_q.put_nowait, msg)
        if span is not None:
            span.finish()
        self.msgr._call_soon(self._send_q.put_nowait, msg)

    def mark_down(self):
        self.msgr._call_soon(self._do_close)

    @property
    def is_connected(self) -> bool:
        return self._writer is not None and not self._closed

    # -- loop-side internals ----------------------------------------------
    def _do_close(self):
        self._closed = True
        for t in self._tasks:
            t.cancel()
        if self._writer:
            self._writer.close()
            self._writer = None
        self.msgr._conn_closed(self)

    async def _write_frame(self, tag: int, payload: bytes):
        w = self._writer
        if w is None:
            raise ConnectionError("not connected")
        if self.msgr.inject_socket_failures:
            if self.msgr.faults.socket_cut(
                    self.msgr.inject_socket_failures):
                # simulate a cut link: kill the transport only; session
                # state stays for resume
                w.transport.abort()
                raise ConnectionError("injected socket failure")
        if self.secure:
            # AES-GCM with the frame tag as AAD: moving a ciphertext
            # under a different tag fails authentication, same as a
            # flipped payload bit
            wire = self.session_key.encrypt(payload, aad=bytes([tag]))
            crc = zlib.crc32(wire) & 0xFFFFFFFF
            frame = struct.pack("<IBI", len(wire) + 5, tag, crc) + wire
        else:
            crc = zlib.crc32(payload) & 0xFFFFFFFF
            frame = struct.pack("<IBI", len(payload) + 5 +
                                (8 if self.session_key else 0), tag, crc)
            frame += payload
            if self.session_key:
                frame += self.session_key.sign(payload)
        w.write(frame)
        await w.drain()

    async def _sender(self, gen: int):
        try:
            while True:
                msg = await self._send_q.get()
                self.out_seq += 1
                msg.seq = self.out_seq
                self._unacked[msg.seq] = msg
                await self._write_frame(TAG_MSG, msg.encode())
        except asyncio.CancelledError:
            pass
        except (ConnectionError, OSError):
            await self._on_transport_fail(gen)

    async def _read_frame(self, r: asyncio.StreamReader):
        hdr = await r.readexactly(9)
        length, tag, crc = struct.unpack("<IBI", hdr)
        body = await r.readexactly(length - 5)
        if self.secure:
            if zlib.crc32(body) & 0xFFFFFFFF != crc:
                raise ConnectionError("frame crc mismatch")
            try:
                payload = self.session_key.decrypt(body,
                                                   aad=bytes([tag]))
            except AuthError as e:
                # tampered or spliced ciphertext: GCM authentication
                # failed — poison the transport, never deliver
                raise ConnectionError(f"secure frame rejected: {e}") \
                    from None
            return tag, payload
        siglen = 8 if self.session_key else 0
        payload = body[:len(body) - siglen]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise ConnectionError("frame crc mismatch")
        if siglen:
            if not self.session_key.verify(payload, body[-8:]):
                raise ConnectionError("frame signature mismatch")
        return tag, payload

    async def _reader(self, r: asyncio.StreamReader, gen: int):
        try:
            while True:
                tag, payload = await self._read_frame(r)
                if tag == TAG_MSG:
                    try:
                        msg = Message.decode(payload)
                    except ValueError:
                        # unknown message TYPE (version skew): the frame
                        # is CRC-valid, so consume it — take the seq from
                        # the fixed header offset, ack, and drop, exactly
                        # so a newer peer doesn't replay it forever
                        seq = struct.unpack_from("<Q", payload, 2)[0]
                        if seq == self.in_seq + 1:
                            self.in_seq = seq
                        await self._write_frame(
                            TAG_ACK, struct.pack("<Q", self.in_seq))
                        continue
                    if msg.seq != self.in_seq + 1:
                        # duplicate (≤ in_seq: replay after a lost ack)
                        # or a GAP (a stale transport's buffered frames
                        # racing the resumed one): drop either, and
                        # RE-ACK the cumulative position so the peer
                        # trims/replays correctly instead of forever
                        await self._write_frame(
                            TAG_ACK, struct.pack("<Q", self.in_seq))
                        continue
                    self.in_seq = msg.seq
                    msg.connection = self
                    # dispatch BEFORE the ack write: the ack await can
                    # raise on a dying transport, and a message whose
                    # in_seq already advanced would then be swallowed —
                    # deliver-then-ack + dedup = exactly-once
                    self.msgr._dispatch(msg)
                    await self._write_frame(
                        TAG_ACK, struct.pack("<Q", msg.seq))
                elif tag == TAG_ACK:
                    (seq,) = struct.unpack("<Q", payload)
                    for s in [s for s in self._unacked if s <= seq]:
                        del self._unacked[s]
                elif tag == TAG_KEEPALIVE:
                    pass
                elif tag == TAG_RESET:
                    raise ConnectionError("peer reset")
        except asyncio.CancelledError:
            pass
        except (asyncio.IncompleteReadError, EOFError, ConnectionError,
                OSError, struct.error, DecodeError):
            # malformed frame/payload = poisoned transport: fault it so
            # the session resumes instead of the reader dying silently
            await self._on_transport_fail(gen)

    async def _on_transport_fail(self, gen: int):
        if self._closed or gen != self._gen:
            return    # a newer transport already took over
        self._gen += 1  # invalidate concurrent failure reports
        self.msgr.transport_faults += 1
        if self._writer:
            self._writer.close()
            self._writer = None
        for t in self._tasks:
            t.cancel()
        self._tasks = []
        if self.outgoing:
            if self.msgr.reconnect:
                if self._reconnect_task and not \
                        self._reconnect_task.done():
                    return  # one reconnect loop is already working
                self._reconnect_task = self.msgr._loop.create_task(
                    self._reconnect())
            else:
                self._closed = True
                self.msgr._conn_closed(self)
                self.msgr._notify_reset(self)
        # incoming: keep the session (in_seq, unacked) registered so the
        # peer can resume — the msgr2 lossless-connection contract; the
        # session dies only via mark_down()/shutdown()

    async def _reconnect(self):
        backoff = 0.02
        while not self._closed:
            try:
                await self.msgr._establish(self, resume=True)
                return
            except _HANDSHAKE_ERRORS:
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2,
                              self.msgr.reconnect_backoff_max)
        self.msgr._notify_reset(self)

    async def _start_io(self, r: asyncio.StreamReader,
                        w: asyncio.StreamWriter, peer_in_seq: int):
        """Common tail of connect/accept: drop acked, REPLAY unacked
        (before the sender task starts, so replays can't interleave
        with new sends), then run reader+sender."""
        self._gen += 1
        gen = self._gen
        self._writer = w
        for s in [s for s in self._unacked if s <= peer_in_seq]:
            del self._unacked[s]
        # reader first: replayed frames get acked WHILE we replay, so a
        # mid-replay transport cut still made progress (the next resume
        # replays only what remains) — without this, a long unacked
        # backlog under failure injection can never fully replay
        self._tasks = [self.msgr._loop.create_task(self._reader(r, gen))]
        if self._unacked:
            # flush per frame during replay: a cut (transport.abort)
            # discards the asyncio write buffer, so without this a large
            # buffered replay loses EVERY frame of the attempt and the
            # session never converges under failure injection
            w.transport.set_write_buffer_limits(0)
            try:
                for seq in sorted(self._unacked):
                    msg = self._unacked.get(seq)
                    if msg is None:
                        continue   # acked concurrently by the new reader
                    await self._write_frame(TAG_MSG, msg.encode())
            finally:
                w.transport.set_write_buffer_limits()
        self._tasks.append(self.msgr._loop.create_task(self._sender(gen)))


class Messenger:
    def __init__(self, entity_name: str, *,
                 keyring_key: CryptoKey | None = None,
                 verifier: ServiceVerifier | None = None,
                 session_ticket=None,
                 mode: str = "crc",
                 inject_socket_failures: int = 0,
                 fault_injector: FaultInjector | None = None,
                 inject_seed: int | None = None,
                 reconnect: bool = True,
                 reconnect_backoff_max: float = 2.0,
                 max_queued: int = 4096):
        """`verifier` makes the accepting side demand an authorizer;
        `session_ticket` (core.auth.SessionTicket, or a zero-arg
        callable returning one — a factory lets long-lived daemons
        present FRESH tickets so reconnects keep working past the
        ticket TTL) makes the connecting side present one.  Both
        None ⇒ AUTH_NONE mode.

        `mode` is the reference's ms_mode: "crc" (integrity) or
        "secure" (AES-GCM frame encryption; requires auth on both
        roles — secure peers refuse to talk to crc peers and vice
        versa, so a cluster runs one mode)."""
        if mode not in ("crc", "secure"):
            raise ValueError(f"unknown ms_mode {mode!r}")
        if mode == "secure" and verifier is None and \
                session_ticket is None:
            raise ValueError(
                "secure mode requires auth (verifier and/or ticket): "
                "there is no session key to encrypt with otherwise")
        self.mode = mode
        self.entity_name = entity_name
        self.my_addr: EntityAddr | None = None
        self.verifier = verifier
        self.session_ticket = session_ticket
        self.keyring_key = keyring_key
        self.inject_socket_failures = inject_socket_failures
        # every injection decision (socket cuts included) routes
        # through this seeded policy table — the deterministic-replay
        # contract lives in msg/fault.py
        self.faults = fault_injector or FaultInjector(seed=inject_seed)
        self.reconnect = reconnect
        self.reconnect_backoff_max = reconnect_backoff_max
        self.max_queued = max_queued
        # core.tracer.Tracer attached by the owning daemon; wire
        # spans are only cut for messages already carrying a trace
        # ctx, so heartbeats/elections stay span-free
        self.tracer = None
        self.dispatchers: list[Dispatcher] = []
        self.connections: list[Connection] = []
        # observability: every EPIPE/ECONNRESET/half-open cut that was
        # absorbed as a clean connection fault (tests assert >0 after
        # killing a peer process instead of grepping for tracebacks)
        self.transport_faults = 0
        self._down = False
        self._server: asyncio.AbstractServer | None = None
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name=f"msgr-{entity_name}",
            daemon=True)
        self._thread.start()
        self._nonce = int.from_bytes(os.urandom(4), "little")

    # -- lifecycle ---------------------------------------------------------
    def add_dispatcher(self, d: Dispatcher):
        self.dispatchers.append(d)

    def bind(self, host: str = "127.0.0.1", port: int = 0) -> EntityAddr:
        fut = asyncio.run_coroutine_threadsafe(
            self._bind(host, port), self._loop)
        self.my_addr = fut.result(10)
        return self.my_addr

    async def _bind(self, host, port):
        self._server = await asyncio.start_server(
            self._accept, host, port)
        sock = self._server.sockets[0]
        return EntityAddr(host, sock.getsockname()[1], self._nonce)

    def shutdown(self):
        async def _stop():
            # cancel, then AWAIT, every task before stopping the loop —
            # stop() in the same callback leaves the cancellations
            # unprocessed and the interpreter prints "Task was
            # destroyed but it is pending!" for each at GC time
            for c in list(self.connections):
                c._closed = True
                c._tasks = []
                c._reconnect_task = None
                if c._writer:
                    c._writer.close()
                    c._writer = None
                self._conn_closed(c)
            if self._server:
                self._server.close()
            # sweep EVERY task on this loop — connection readers/
            # senders, reconnect loops, AND in-flight _accept handlers
            # (start_server spawns those; we hold no reference to them).
            # Loop until drained: a cross-thread callback queued before
            # _down was set can spawn a task while gather() yields
            while True:
                pending = [t for t in asyncio.all_tasks()
                           if t is not asyncio.current_task()]
                if not pending:
                    break
                for t in pending:
                    t.cancel()
                await asyncio.gather(*pending, return_exceptions=True)
            self._loop.stop()
        if self._down or self._loop.is_closed():
            return    # double shutdown
        self._down = True
        try:
            asyncio.run_coroutine_threadsafe(_stop(), self._loop)
        except RuntimeError:
            return    # loop already gone
        self._thread.join(timeout=5)
        if not self._thread.is_alive():
            self._loop.close()

    # -- connecting --------------------------------------------------------
    def connect_to(self, addr: EntityAddr) -> Connection:
        con = Connection(self, addr, outgoing=True)
        fut = asyncio.run_coroutine_threadsafe(
            self._establish(con, resume=False), self._loop)
        fut.result(10)
        self.connections.append(con)
        return con

    def connect_to_lazy(self, addr: EntityAddr) -> Connection:
        """Non-blocking connect: returns immediately; messages queue and
        flow once the handshake lands; failures retry via the normal
        reconnect loop.  REQUIRED when calling from a dispatch handler —
        the blocking connect_to would deadlock the messenger's own loop."""
        con = Connection(self, addr, outgoing=True)
        self.connections.append(con)

        async def _first():
            try:
                await self._establish(con, resume=False)
            except _HANDSHAKE_ERRORS:
                if self.reconnect:
                    await con._reconnect()  # loops until success/close
                else:
                    con._closed = True
                    self._conn_closed(con)
                    self._notify_reset(con)

        def _spawn():
            if self._down:
                return    # raced shutdown(): don't spawn past the sweep
            con._reconnect_task = self._loop.create_task(_first())

        # create_task is NOT thread-safe and won't wake a foreign
        # loop's selector; route through the self-pipe (_call_soon also
        # absorbs the post-shutdown closed-loop RuntimeError)
        self._call_soon(_spawn)
        return con

    async def _establish(self, con: Connection, resume: bool):
        r, w = await asyncio.open_connection(
            con.peer_addr.host, con.peer_addr.port)
        w.write(BANNER)
        hello = {
            "entity": self.entity_name,
            "nonce": self._nonce,
            "in_seq": con.in_seq if resume else 0,
            "resume": resume,
            "mode": self.mode,
        }
        # resolve the ticket per attempt: a factory re-mints, so a
        # reconnect hours later presents a fresh (unexpired) ticket
        ticket = (self.session_ticket()
                  if callable(self.session_ticket)
                  else self.session_ticket)
        if ticket is not None:
            # ticket only; the proof answers the SERVER's challenge in
            # the next round (a client-chosen nonce would make captured
            # handshakes replayable)
            hello["authorizer"] = {
                "entity": ticket.entity,
                "ticket": ticket.ticket.hex(),
            }
        payload = json.dumps(hello).encode()
        w.write(struct.pack("<I", len(payload)) + payload)
        await w.drain()
        banner = await r.readexactly(len(BANNER))
        if banner != BANNER:
            raise ConnectionError("bad banner")
        reply = await _read_json(r)
        if "challenge" in reply:
            if ticket is None:
                raise ConnectionError("server demands auth, no ticket")
            proof = ticket.session_key.sign(
                bytes.fromhex(reply["challenge"]))
            payload = json.dumps({"proof": proof.hex()}).encode()
            w.write(struct.pack("<I", len(payload)) + payload)
            await w.drain()
            reply = await _read_json(r)
        if reply.get("error"):
            raise ConnectionError(f"handshake refused: {reply['error']}")
        if reply.get("mode", "crc") != self.mode:
            raise ConnectionError(
                f"ms_mode mismatch: we={self.mode} "
                f"peer={reply.get('mode', 'crc')}")
        con.peer_name = reply.get("entity")
        peer_nonce = reply.get("nonce")
        if (resume and peer_nonce is not None
                and con.peer_nonce is not None
                and peer_nonce != con.peer_nonce):
            # the peer PROCESS died and came back (kill -9 + respawn on
            # the same addr): its session state — our in_seq as it knew
            # it, its out stream — is gone.  Rebase instead of replaying
            # old seqs at a server that would see them as a gap and
            # re-ack 0 forever: restart its incoming stream at 1 by
            # renumbering our unacked backlog in order, and accept its
            # fresh outgoing stream from 1.  Dedup against the old
            # incarnation is impossible (it took its receive state to
            # the grave), so redelivery of acked-but-unapplied work is
            # the application contract, same as any daemon restart.
            replay = [con._unacked[s] for s in sorted(con._unacked)]
            con._unacked = {}
            for i, m in enumerate(replay, 1):
                m.seq = i
                con._unacked[i] = m
            con.out_seq = len(replay)
            con.in_seq = 0
        if peer_nonce is not None:
            con.peer_nonce = peer_nonce
        if ticket is not None:
            con.session_key = ticket.session_key
        con.secure = (self.mode == "secure")
        if con.secure and con.session_key is None:
            raise ConnectionError("secure mode without a session key")
        await con._start_io(r, w, reply.get("in_seq", 0))

    # -- accepting ---------------------------------------------------------
    async def _accept(self, r: asyncio.StreamReader,
                      w: asyncio.StreamWriter):
        try:
            banner = await r.readexactly(len(BANNER))
            if banner != BANNER:
                w.close()
                return
            hello = await _read_json(r)
            session_key = None
            banner_sent = False
            if hello.get("mode", "crc") != self.mode:
                payload = json.dumps({
                    "error": f"ms_mode mismatch: we={self.mode} "
                             f"peer={hello.get('mode', 'crc')}"}
                ).encode()
                w.write(BANNER + struct.pack("<I", len(payload))
                        + payload)
                await w.drain()
                w.close()
                return
            if self.verifier is not None:
                try:
                    authz = hello.get("authorizer")
                    if not authz:
                        raise AuthError("authorizer required")
                    # challenge-response: WE pick the nonce, so captured
                    # handshakes cannot be replayed
                    challenge = os.urandom(16)
                    payload = json.dumps(
                        {"challenge": challenge.hex()}).encode()
                    w.write(BANNER + struct.pack("<I", len(payload))
                            + payload)
                    banner_sent = True
                    await w.drain()
                    answer = await _read_json(r)
                    entity, session_key, _caps = \
                        self.verifier.verify_authorizer(
                            {"entity": authz["entity"],
                             "ticket": bytes.fromhex(authz["ticket"]),
                             "proof": bytes.fromhex(answer["proof"])},
                            challenge)
                    # the hello's entity is unauthenticated; bind the
                    # session to the ticket-verified identity so a valid
                    # ticket for A cannot splice into B's session
                    if hello.get("entity") != entity:
                        raise AuthError(
                            "hello entity does not match ticket")
                except (AuthError, KeyError, ValueError) as e:
                    payload = json.dumps({"error": str(e)}).encode()
                    prefix = b"" if banner_sent else BANNER
                    w.write(prefix + struct.pack("<I", len(payload))
                            + payload)
                    await w.drain()
                    w.close()
                    return
        except (asyncio.IncompleteReadError, EOFError, OSError,
                ValueError, KeyError, json.JSONDecodeError,
                struct.error):
            w.close()
            return
        # session resume: find the existing session from this exact peer
        # incarnation — (entity, nonce), not entity alone, so two
        # connections from one entity can't splice each other's state
        con = None
        if hello.get("resume"):
            for c in self.connections:
                if (c.peer_name == hello["entity"]
                        and c.peer_nonce == hello.get("nonce")
                        and not c.outgoing and not c._closed):
                    con = c
                    break
        if con is None:
            con = Connection(self, None, outgoing=False)
            con.peer_name = hello["entity"]
            con.peer_nonce = hello.get("nonce")
            self.connections.append(con)
            for d in self.dispatchers:
                d.ms_handle_accept(con)
        con.session_key = session_key
        con.secure = (self.mode == "secure")
        if con.secure and session_key is None:
            # secure without an authenticated key is a contradiction;
            # the ctor enforces verifier-presence, so this only trips
            # if auth was skipped by a code path change — refuse loudly
            payload = json.dumps(
                {"error": "secure mode without session key"}).encode()
            prefix = b"" if banner_sent else BANNER
            w.write(prefix + struct.pack("<I", len(payload)) + payload)
            await w.drain()
            w.close()
            return
        reply = {"entity": self.entity_name, "in_seq": con.in_seq,
                 "nonce": self._nonce, "mode": self.mode}
        payload = json.dumps(reply).encode()
        prefix = b"" if banner_sent else BANNER
        w.write(prefix + struct.pack("<I", len(payload)) + payload)
        await w.drain()
        # cancel stale tasks from a previous transport incarnation
        for t in con._tasks:
            t.cancel()
        await con._start_io(r, w, hello.get("in_seq", 0))

    # -- dispatch ----------------------------------------------------------
    def _dispatch(self, msg: Message):
        tracer = self.tracer
        span = None
        if tracer is not None and tracer.enabled:
            ctx = getattr(msg, "trace", None)
            if ctx:
                span = tracer.start_span(
                    f"wire_recv:{type(msg).__name__}", parent=ctx,
                    tags={"layer": "wire"})
        try:
            for d in self.dispatchers:
                try:
                    if d.ms_dispatch(msg):
                        return
                except Exception:  # noqa: BLE001 — a dispatcher must
                    import traceback  # not kill the messenger thread
                    traceback.print_exc()
                    return
            # undispatched messages are dropped, as the reference does
        finally:
            if span is not None:
                span.finish()

    def _notify_reset(self, con: Connection):
        for d in self.dispatchers:
            d.ms_handle_reset(con)

    def _conn_closed(self, con: Connection):
        if con in self.connections:
            self.connections.remove(con)

    def _call_soon(self, fn, *args):
        try:
            self._loop.call_soon_threadsafe(fn, *args)
        except RuntimeError:
            pass    # loop closed by shutdown(); nothing left to do

"""Policy-driven network fault injection for the messenger.

Reference behavior re-created (``src/msg/Messenger.h`` ms_inject_*
knobs + the ceph_manager/qa thrasher network-partition tooling): the
single ``ms_inject_socket_failures`` cut is generalised into a
per-peer-pair **policy table** — message drop / delay / duplicate /
reorder probabilities and **directed partitions** (A⇸B while B→A
still flows).

Determinism contract: every verdict is a pure function of
``(seed, src, dst, n)`` where ``n`` is the per-pair message counter —
NOT of thread interleaving or wall clock.  Two clusters driven with
the same seed see the n-th message of every peer pair suffer the same
fate, so a thrash failure replays from the logged seed alone.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field

# verdicts, in evaluation order (first matching probability band wins)
DROP = "drop"
DELAY = "delay"
DUP = "dup"
REORDER = "reorder"
PARTITION = "partition"


@dataclass
class FaultRule:
    """One peer-pair policy.  Probabilities are independent bands of a
    single uniform draw (cumulative), so drop+dup+reorder+delay must
    sum to ≤ 1.0."""
    drop: float = 0.0
    delay: float = 0.0        # probability of delaying a message
    delay_ms: float = 20.0    # how long a delayed message waits
    dup: float = 0.0
    reorder: float = 0.0
    reorder_ms: float = 40.0  # hold-back window (later sends overtake)
    partition: bool = False   # directed: src→dst blackholed entirely

    def active(self) -> bool:
        return bool(self.partition or self.drop or self.delay
                    or self.dup or self.reorder)

    def to_dict(self) -> dict:
        return {"drop": self.drop, "delay": self.delay,
                "delay_ms": self.delay_ms, "dup": self.dup,
                "reorder": self.reorder, "reorder_ms": self.reorder_ms,
                "partition": self.partition}


@dataclass
class FaultDecision:
    verdict: str | None
    hold_s: float = 0.0       # enqueue delay for DELAY/REORDER


class FaultInjector:
    """Per-messenger fault policy table + seeded RNG.

    Rules are keyed ``(src, dst)`` where either side may be ``"*"``;
    lookup precedence is (src,dst) > (src,*) > (*,dst) > (*,*) so a
    targeted partition overrides a blanket drop policy.
    """

    def __init__(self, seed: int | None = None):
        if seed is None:
            seed = random.SystemRandom().randrange(1 << 31)
        self.seed = int(seed)
        # rng: the legacy socket-cut draw (ms_inject_socket_failures)
        # and any jitter — seeded so thrash runs replay from the seed
        self.rng = random.Random(self.seed)
        self._rules: dict[tuple[str, str], FaultRule] = {}
        self._counters: dict[tuple[str, str], int] = {}
        self._lock = threading.Lock()
        # mutation epoch: bumped on every rule change so hot paths can
        # skip the table scan entirely while no rules are installed
        self._active = False

    # -- policy management (thread-safe; callable from admin socket) ---
    def set_rule(self, src: str = "*", dst: str = "*", **kw) -> FaultRule:
        with self._lock:
            rule = self._rules.get((src, dst))
            if rule is None:
                rule = FaultRule()
                self._rules[(src, dst)] = rule
            for k, v in kw.items():
                if not hasattr(rule, k):
                    raise KeyError(f"unknown fault knob {k!r}")
                setattr(rule, k, type(getattr(rule, k))(v))
            self._refresh_active()
            return rule

    def partition(self, dst: str, src: str = "*"):
        """Install a DIRECTED partition: src→dst blackholed (the
        reverse direction is untouched — install on the peer's
        injector for a full split)."""
        return self.set_rule(src, dst, partition=True)

    def heal(self, src: str | None = None, dst: str | None = None):
        """Remove rules.  No args = everything; src/dst filter."""
        with self._lock:
            for key in list(self._rules):
                if (src is None or key[0] == src) and \
                        (dst is None or key[1] == dst):
                    del self._rules[key]
            self._refresh_active()

    def clear(self):
        self.heal()

    def describe(self) -> dict:
        with self._lock:
            return {
                "seed": self.seed,
                "rules": {f"{s}>{d}": r.to_dict()
                          for (s, d), r in self._rules.items()},
                "counters": {f"{s}>{d}": n
                             for (s, d), n in self._counters.items()},
            }

    def _refresh_active(self):
        self._active = any(r.active() for r in self._rules.values())

    @property
    def active(self) -> bool:
        return self._active

    # -- verdicts ------------------------------------------------------
    def _match(self, src: str, dst: str) -> FaultRule | None:
        rules = self._rules
        for key in ((src, dst), (src, "*"), ("*", dst), ("*", "*")):
            r = rules.get(key)
            if r is not None and r.active():
                return r
        return None

    @staticmethod
    def _verdict_for(rule: FaultRule, u: float) -> str | None:
        """Map one uniform draw to a verdict via cumulative bands."""
        if rule.partition:
            return PARTITION
        edge = rule.drop
        if u < edge:
            return DROP
        edge += rule.dup
        if u < edge:
            return DUP
        edge += rule.reorder
        if u < edge:
            return REORDER
        edge += rule.delay
        if u < edge:
            return DELAY
        return None

    def _draw(self, src: str, dst: str, n: int) -> float:
        # string seeding is sha512-based in CPython: stable across
        # processes and PYTHONHASHSEED, so the n-th message of a pair
        # draws the same uniform in every run with this seed
        return random.Random(
            f"{self.seed}|{src}>{dst}|{n}").random()

    def decide(self, src: str, dst: str) -> FaultDecision:
        """Fate of the next message src→dst; advances the pair counter."""
        with self._lock:
            rule = self._match(src, dst)
            if rule is None:
                return FaultDecision(None)
            key = (src, dst)
            n = self._counters.get(key, 0)
            self._counters[key] = n + 1
        v = self._verdict_for(rule, self._draw(src, dst, n))
        if v == DELAY:
            return FaultDecision(v, rule.delay_ms / 1000.0)
        if v == REORDER:
            return FaultDecision(v, rule.reorder_ms / 1000.0)
        return FaultDecision(v)

    def preview(self, src: str, dst: str, count: int) -> list[str | None]:
        """The fault schedule for the first `count` messages of a pair
        — pure (no counter advance).  Two injectors with equal seeds
        and rules return identical schedules; this is the acceptance
        hook for seeded reproducibility."""
        with self._lock:
            rule = self._match(src, dst)
        if rule is None:
            return [None] * count
        return [self._verdict_for(rule, self._draw(src, dst, n))
                for n in range(count)]

    def preview_pairs(self, pairs, count: int) -> dict[str, list]:
        """Site-level twin of `preview`: the fault schedule for each
        directed ``(src, dst)`` pair over its first `count` messages,
        keyed ``"src>dst"``.  Pure — this is how a whole-site event
        (blackout, WAN degradation over every inter-site pair) proves
        it replays from the logged seed."""
        return {f"{s}>{d}": self.preview(s, d, count) for s, d in pairs}

    def socket_cut(self, every: int) -> bool:
        """Legacy ms_inject_socket_failures draw, through the seeded
        per-messenger RNG (was: module-global ``random``)."""
        with self._lock:
            return self.rng.randrange(every) == 0


def site_pairs(a: list[str], b: list[str],
               bidirectional: bool = True) -> list[tuple[str, str]]:
    """All directed inter-site (src, dst) entity-name pairs — the unit
    the site-level primitives (partition_sites, blackout, slow-WAN)
    operate on.  Deterministic order: sorted within each site."""
    pairs = [(s, d) for s in sorted(a) for d in sorted(b)]
    if bidirectional:
        pairs += [(s, d) for s in sorted(b) for d in sorted(a)]
    return pairs


def injector_from_config(cfg) -> FaultInjector:
    """Build a FaultInjector from ms_inject_* options; a blanket
    (*→*) rule is installed when any probability is non-zero."""
    seed = int(cfg.get("ms_inject_seed") or 0) or None
    fi = FaultInjector(seed=seed)
    kw = {}
    for opt, knob in (("ms_inject_drop_prob", "drop"),
                      ("ms_inject_delay_prob", "delay"),
                      ("ms_inject_delay_ms", "delay_ms"),
                      ("ms_inject_dup_prob", "dup"),
                      ("ms_inject_reorder_prob", "reorder"),
                      ("ms_inject_reorder_ms", "reorder_ms")):
        v = cfg.get(opt)
        if v:
            kw[knob] = float(v)
    if any(k in kw for k in ("drop", "delay", "dup", "reorder")):
        fi.set_rule("*", "*", **kw)
    return fi

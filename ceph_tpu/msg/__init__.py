"""Messenger — the distributed communication backend (L2).

Reference: ``src/msg/``, ``src/msg/async/`` (SURVEY.md §3.2).  The
reference's AsyncMessenger is N epoll worker threads; here one asyncio
event loop per Messenger carries all connections (the GIL makes extra
loops pure overhead), with the same externally visible contract:
per-connection ordered delivery, typed messages, authenticated and
CRC-protected frames, reconnect with session resume, fault injection.

The DATA plane of this framework deliberately does NOT ride this
messenger: bulk chunk movement between TPU shards is XLA collectives
over ICI (``ceph_tpu.parallel``) — SURVEY.md §3.2's "TPU-native
equivalent".  This messenger is the control plane (maps, peering,
heartbeats, client ops).
"""

from .fault import FaultInjector, FaultRule  # noqa: F401
from .message import (MSG_REGISTRY, Message, MGenericPing,  # noqa: F401
                      MGenericReply, register_message)
from .messenger import (Connection, Dispatcher, EntityAddr,  # noqa: F401
                        Messenger)

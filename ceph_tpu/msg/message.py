"""Typed messages — the `Message` hierarchy and registry.

Reference: ``src/msg/Message.{h,cc}`` + the ~150 ``src/messages/*.h``
classes (SURVEY.md §3.2).  Each RPC is a class with a numeric TYPE;
encode/decode run through the versioned codec so message evolution
follows the same compat rules as the reference.
"""

from __future__ import annotations

from ..core.encoding import Decoder, Encoder

MSG_REGISTRY: dict[int, type["Message"]] = {}


def register_message(cls: type["Message"]) -> type["Message"]:
    if cls.TYPE in MSG_REGISTRY and MSG_REGISTRY[cls.TYPE] is not cls:
        raise ValueError(
            f"message type {cls.TYPE} already taken by "
            f"{MSG_REGISTRY[cls.TYPE].__name__}")
    MSG_REGISTRY[cls.TYPE] = cls
    return cls


class Message:
    """Base message: subclasses set TYPE and implement
    encode_payload/decode_payload; header bookkeeping (seq, priority)
    is filled by the connection."""

    TYPE = 0
    VERSION = 1
    COMPAT = 1
    PRIORITY_DEFAULT = 127
    PRIORITY_HIGH = 196

    def __init__(self):
        self.seq = 0
        self.priority = self.PRIORITY_DEFAULT
        #: set on received messages: the Connection it arrived on
        self.connection = None

    # subclass hooks ------------------------------------------------------
    def encode_payload(self, enc: Encoder):
        pass

    def decode_payload(self, dec: Decoder, version: int):
        pass

    # framing -------------------------------------------------------------
    def encode(self) -> bytes:
        enc = Encoder()
        enc.u16(self.TYPE)
        enc.u64(self.seq)
        enc.u8(self.priority)
        with enc.struct_block(self.VERSION, self.COMPAT):
            self.encode_payload(enc)
        return bytes(enc)

    @staticmethod
    def decode(data) -> "Message":
        dec = Decoder(data)
        mtype = dec.u16()
        cls = MSG_REGISTRY.get(mtype)
        if cls is None:
            raise ValueError(f"unknown message type {mtype}")
        msg = cls.__new__(cls)
        Message.__init__(msg)
        msg.seq = dec.u64()
        msg.priority = dec.u8()
        with dec.struct_block(cls.VERSION) as blk:
            msg.decode_payload(blk.dec, blk.version)
        return msg

    def __repr__(self):
        return f"{type(self).__name__}(seq={self.seq})"


@register_message
class MGenericPing(Message):
    """Generic liveness probe (the MPing shape)."""

    TYPE = 1

    def __init__(self, stamp: float = 0.0):
        super().__init__()
        self.stamp = stamp

    def encode_payload(self, enc):
        enc.f64(self.stamp)

    def decode_payload(self, dec, version):
        self.stamp = dec.f64()


@register_message
class MGenericReply(Message):
    """Generic ack carrying a JSON-ish string result (test scaffolding
    and simple control RPCs)."""

    TYPE = 2

    def __init__(self, what: str = "", result: int = 0):
        super().__init__()
        self.what = what
        self.result = result

    def encode_payload(self, enc):
        enc.string(self.what)
        enc.s32(self.result)

    def decode_payload(self, dec, version):
        self.what = dec.string()
        self.result = dec.s32()

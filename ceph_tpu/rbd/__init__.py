"""RBD — block images striped over RADOS objects (SURVEY.md §3.9)."""

from .image import Image, RBD, ImageNotFound  # noqa: F401

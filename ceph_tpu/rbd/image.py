"""RBD image layer — block devices over librados.

Reference behavior re-created (``src/librbd/``: ``ImageCtx.cc``,
``io/ImageRequest.cc``, ``io/ObjectRequest.cc``; SURVEY.md §3.9):

- an image is a **header object** (``rbd_header.<name>``, omap:
  size/order/stripe params/snapshot table) plus **data objects**
  (``rbd_data.<name>.<objectno:016x>``), sparse — absent objects read
  as zeros;
- image I/O maps byte ranges through the Striper
  (`ceph_tpu.osdc.striper`) and fans out per-object ops through the
  Objecter; RBD's default layout is stripe_count=1 so an object is a
  contiguous 2^order-byte slice;
- **snapshots**: create_snap stamps a new snap id in the header; data
  objects are copied-on-first-write afterwards (clone object
  ``<obj>@<snap_id>``), so reads at a snapshot see the image exactly
  as it was (the reference uses RADOS self-managed snaps + SnapContext
  in the OSD; here the COW happens at the image layer over plain
  RADOS objects — same observable semantics for image I/O).

Cited reference files per SURVEY.md §0 convention (mount was empty —
paths, no line numbers).
"""

from __future__ import annotations

import json

from ..osdc.striper import FileLayout, file_to_extents


class ImageNotFound(KeyError):
    pass


def _header_oid(name: str) -> str:
    return f"rbd_header.{name}"


def _data_oid(name: str, objectno: int) -> str:
    return f"rbd_data.{name}.{objectno:016x}"


def _journal_oid(name: str) -> str:
    return f"rbd_journal.{name}"


# -- encryption (reference src/librbd/crypto/: LUKS-style envelope) ----
# A random data-encryption key (DEK) is wrapped by a key-encryption
# key derived from the passphrase (PBKDF2-SHA256); the wrapped DEK
# lives in the header, so the passphrase can be verified (and in
# principle rotated) without re-encrypting data.  Data objects hold
# AES-256-GCM envelopes of the object's logical plaintext — partial
# writes read-modify-write the object (the reference's LUKS layer uses
# XTS sectors for in-place writes; whole-object GCM trades that for
# authenticated reads at slice scale).

def _derive_kek(passphrase: str, salt: bytes) -> bytes:
    import hashlib
    return hashlib.pbkdf2_hmac("sha256", passphrase.encode(), salt,
                               100_000, dklen=32)


def _seal(key: bytes, plaintext: bytes, aad: bytes = b"") -> bytes:
    # CryptoKey carries the AES-GCM-or-HMAC-stream dependency gate
    # (core/auth.py): same nonce+ct framing either way
    from ..core.auth import CryptoKey
    return CryptoKey(key).encrypt(plaintext, aad)


def _unseal(key: bytes, blob: bytes, aad: bytes = b"") -> bytes:
    from ..core.auth import CryptoKey
    return CryptoKey(key).decrypt(bytes(blob), aad)


def _is_data_suffix(rest: str) -> bool:
    """True iff `rest` is '<16-hex-objno>' or '<16-hex-objno>@<int>'
    (a snapshot clone) — the only shapes this image's data objects
    take.  Guards every prefix scan against sibling images whose name
    extends ours ("foo" vs "foo.123")."""
    base, _, clone = rest.partition("@")
    if len(base) != 16 or any(c not in "0123456789abcdef"
                              for c in base):
        return False
    return clone == "" or clone.isdigit()


def _objmap_oid(name: str, snap_id: int | None = None) -> str:
    """Object-map object (reference src/librbd/object_map/): the head
    map plus one frozen copy per snapshot."""
    base = f"rbd_object_map.{name}"
    return base if snap_id is None else f"{base}.{snap_id}"


# object-map states (reference OBJECT_{NONEXISTENT,EXISTS,EXISTS_CLEAN})
OM_NONE = 0        # no data object
OM_DIRTY = 1       # exists, written since the last snapshot
OM_CLEAN = 2       # exists, unchanged since the last snapshot


class RBD:
    """Pool-level image operations (reference ``librbd::RBD``)."""

    def create(self, ioctx, name: str, size: int, *, order: int = 22,
               stripe_unit: int | None = None, stripe_count: int = 1,
               journaling: bool = False, primary: bool = True,
               object_map: bool = True, mirror_snapshot: bool = False):
        if size < 0:
            raise ValueError("image size must be >= 0")
        if journaling and mirror_snapshot:
            raise ValueError(
                "mirroring is journal- OR snapshot-based, not both")
        if _header_oid(name) in ioctx.list_objects():
            raise ValueError(f"image {name!r} exists")
        object_size = 1 << order
        su = stripe_unit if stripe_unit else object_size
        layout = FileLayout(stripe_unit=su, stripe_count=stripe_count,
                            object_size=object_size)
        layout.validate()
        hdr = {
            "size": size, "order": order,
            "stripe_unit": su, "stripe_count": stripe_count,
            "snap_seq": 0, "snaps": {},
            # journaling feature + mirror-primary flag (reference
            # librbd journaling feature bit / mirror image state).
            # `primary` is set at create so a mirror bootstrap writes
            # the non-primary header atomically (no primary window)
            "journaling": journaling, "primary": primary,
            # object-map + fast-diff feature (reference librbd
            # object-map/fast-diff feature bits, on by default)
            "object_map": object_map,
        }
        if mirror_snapshot:
            # snapshot-based mirroring mode (reference `rbd mirror
            # image enable <img> snapshot`): no journal; the daemon
            # ships object-map-assisted deltas between mirror snaps
            hdr["mirror_mode"] = "snapshot"
        ioctx.omap_set(_header_oid(name), {
            "header": json.dumps(hdr).encode()})

    def open(self, ioctx, name: str, snapshot: str | None = None,
             passphrase: str | None = None) -> "Image":
        return Image(ioctx, name, snapshot=snapshot,
                     passphrase=passphrase)

    def clone(self, ioctx, parent: str, snap_name: str, child: str):
        """COW child image from a protected parent snapshot
        (reference ``librbd::clone``): the child starts empty — reads
        of unwritten objects fall through to parent@snap; the first
        write to an object copies it up (reference copyup)."""
        with Image(ioctx, parent, read_only=True) as p:
            snap = p._hdr["snaps"].get(snap_name)
            if snap is None:
                raise ImageNotFound(f"no snapshot {snap_name!r}")
            if not snap.get("protected"):
                raise ValueError(
                    f"snapshot {snap_name!r} is not protected "
                    "(clone requires protection so the parent data "
                    "cannot vanish under the child)")
            self.create(ioctx, child, snap["size"],
                        order=p._hdr["order"],
                        stripe_unit=p._hdr["stripe_unit"],
                        stripe_count=p._hdr["stripe_count"])
        with Image(ioctx, child) as c:
            c._hdr["parent"] = {"image": parent, "snap": snap_name,
                                "snap_id": snap["id"],
                                "overlap": snap["size"]}
            c._save_header()
        with Image(ioctx, parent, read_only=True) as p:
            kids = p._hdr["snaps"][snap_name].setdefault(
                "children", [])
            if child not in kids:
                kids.append(child)
            p._save_header()

    def children(self, ioctx, parent: str, snap_name: str
                 ) -> list[str]:
        with Image(ioctx, parent, read_only=True) as p:
            snap = p._hdr["snaps"].get(snap_name)
            return list((snap or {}).get("children", []))

    def list(self, ioctx) -> list[str]:
        pre = "rbd_header."
        return sorted(o[len(pre):] for o in ioctx.list_objects()
                      if o.startswith(pre))

    # -- live migration (reference rbd migration prepare/execute/
    # commit/abort, src/librbd/migration/) --------------------------------
    def migration_prepare(self, src_ioctx, src: str, dst_ioctx,
                          dst: str):
        """Link a new target image to the source: clients switch to
        the target immediately (reads of uncopied objects fall
        through to the source; writes copy-up first), while the
        source refuses writes for the duration."""
        with Image(src_ioctx, src, read_only=True) as s:
            if s._hdr.get("encryption") is not None:
                raise ValueError(
                    "migrate after decrypting (encrypted migration "
                    "is unsupported)")
            if s._hdr.get("snaps"):
                raise ValueError(
                    "remove/flatten snapshots before migrating")
            if s._hdr.get("migrating"):
                raise ValueError(f"{src!r} is already migrating")
            if s._hdr.get("parent") is not None:
                # migration reads only the source's LOCAL objects;
                # parent-backed bytes would silently vanish
                raise ValueError("flatten the clone before migrating")
            self.create(dst_ioctx, dst, s._hdr["size"],
                        order=s._hdr["order"],
                        stripe_unit=s._hdr["stripe_unit"],
                        stripe_count=s._hdr["stripe_count"],
                        journaling=bool(s._hdr.get("journaling")),
                        primary=bool(s._hdr.get("primary", True)))
            src_size = s._hdr["size"]
            s._hdr["migrating"] = True
            s._save_header()
        with Image(dst_ioctx, dst) as d:
            d._hdr["migration_source"] = {
                "pool": src_ioctx.pool_name, "image": src,
                # like a clone's parent overlap: a shrink clamps it so
                # regrown space reads zeros, never stale source bytes
                "overlap": src_size}
            d._save_header()

    def _migration_pair(self, dst_ioctx, dst):
        d = Image(dst_ioctx, dst)
        mig = d._hdr.get("migration_source")
        if mig is None:
            d.close()
            raise ValueError(f"{dst!r} is not a migration target")
        src_io = dst_ioctx.rados.open_ioctx(mig["pool"])
        return d, src_io, mig["image"]

    def migration_execute(self, dst_ioctx, dst: str) -> int:
        """Background copy of every not-yet-copied object; → number
        copied this pass."""
        from ..osdc.librados import ObjectNotFound
        d, src_io, src = self._migration_pair(dst_ioctx, dst)
        copied: set[int] = set()
        try:
            limit = min(
                d._hdr["size"],
                d._hdr["migration_source"].get("overlap",
                                               d._hdr["size"]))
            nobj = -(-limit // d.layout.object_size)
            for objno in range(nobj):
                if d._object_exists(objno):
                    continue
                raw = d._migration_bytes(objno)
                if not raw:
                    continue            # sparse in the source too
                dst_ioctx.write_full(_data_oid(dst, objno), raw)
                copied.add(objno)
            d._objmap_mark(copied)      # ONE map rewrite per pass
        finally:
            d.close()
        return len(copied)

    def migration_commit(self, dst_ioctx, dst: str):
        """Finish: every object must be local; the source image is
        removed and the target stands alone."""
        from ..osdc.librados import ObjectNotFound
        d, src_io, src = self._migration_pair(dst_ioctx, dst)
        try:
            limit = min(
                d._hdr["size"],
                d._hdr["migration_source"].get("overlap",
                                               d._hdr["size"]))
            nobj = -(-limit // d.layout.object_size)
            for objno in range(nobj):
                if d._object_exists(objno):
                    continue
                try:
                    src_io.stat(_data_oid(src, objno))
                except ObjectNotFound:
                    continue            # sparse: nothing to copy
                raise ValueError(
                    f"object {objno} not copied yet — run "
                    "migration_execute to completion first")
            d._hdr.pop("migration_source", None)
            d._save_header()
        finally:
            d.close()
        with Image(src_io, src) as s:
            s._hdr.pop("migrating", None)
            s._save_header()
        self.remove(src_io, src)

    def migration_abort(self, dst_ioctx, dst: str):
        """Back out: the target disappears, the source resumes."""
        d, src_io, src = self._migration_pair(dst_ioctx, dst)
        d._hdr.pop("migration_source", None)
        d._save_header()
        d.close()
        self.remove(dst_ioctx, dst)
        with Image(src_io, src) as s:
            s._hdr.pop("migrating", None)
            s._save_header()

    def remove(self, ioctx, name: str):
        from ..osdc.librados import ObjectNotFound
        img = Image(ioctx, name)
        # every abort condition FIRST: only mutate the parent's
        # children list once the image is irrevocably being removed —
        # detaching before an abort would let unprotect+remove_snap on
        # the parent succeed while this surviving clone still depends
        # on it (parent-backed reads would fail: data loss)
        for sname, snap in img._hdr.get("snaps", {}).items():
            if snap.get("protected") or snap.get("children"):
                img.close()
                raise ValueError(
                    f"image {name!r} has protected snapshot "
                    f"{sname!r}"
                    + (f" with children {snap['children']}"
                       if snap.get("children") else "")
                    + " — flatten children and unprotect first")
        parent = img._hdr.get("parent")
        if parent is not None:
            # detach from the parent snapshot's children list, or the
            # protected/children guard would wedge the parent forever
            # behind a child that no longer exists
            try:
                with Image(ioctx, parent["image"],
                           read_only=True) as p:
                    snap = p._hdr["snaps"].get(parent["snap"])
                    if snap is not None and \
                            name in snap.get("children", []):
                        snap["children"].remove(name)
                        p._save_header()
            except ImageNotFound:
                pass
        # data objects: the suffix after "rbd_data.<name>." must be
        # the 16-hex objno (optionally "@<snapclone>") — a plain
        # prefix match would also destroy image "foo.123"'s objects
        # when removing "foo"
        pre = f"rbd_data.{name}."
        for o in ioctx.list_objects():
            if o.startswith(pre) and _is_data_suffix(o[len(pre):]):
                ioctx.remove(o)
        # drop the journal object too: a re-created image under the
        # same name must not inherit stale head_seq/mirror_position/
        # untrimmed events (a mirror daemon would skip or misapply the
        # new image's events)
        try:
            ioctx.remove(_journal_oid(name))
        except ObjectNotFound:
            pass
        # and the object maps: head + every possible snap id (snap
        # ids are 1..snap_seq; enumerating exactly also collects the
        # orphan a crash-interrupted create_snap may have left, and —
        # unlike a prefix scan — can never touch a sibling image's
        # maps: "rbd_object_map.foo.123" is image foo.123's HEAD map)
        maps = [_objmap_oid(name)] + [
            _objmap_oid(name, sid)
            for sid in range(1, img._hdr.get("snap_seq", 0) + 2)]
        for om in maps:
            try:
                ioctx.remove(om)
            except ObjectNotFound:
                pass
        ioctx.remove(_header_oid(name))
        img.close()


class Image:
    """One open image (reference ``librbd::Image``).  When opened at a
    snapshot the image is read-only and reads resolve through the COW
    clone chain."""

    def __init__(self, ioctx, name: str, snapshot: str | None = None,
                 read_only: bool = False,
                 passphrase: str | None = None):
        self.ioctx = ioctx
        self.name = name
        self._load_header()
        self.snap_id = None
        self._lock_cookie = None
        self._read_only = read_only
        self._passphrase = passphrase
        self._dek: bytes | None = None
        self._locked = False
        enc = self._hdr.get("encryption")
        if enc is not None:
            if passphrase is None:
                # header-only use (remove, migration bookkeeping,
                # list_snaps) needs no DEK: lock the DATA path instead
                # of refusing the open — an image whose passphrase is
                # lost must still be removable
                self._locked = True
            else:
                kek = _derive_kek(passphrase,
                                  bytes.fromhex(enc["salt"]))
                try:
                    self._dek = _unseal(
                        kek, bytes.fromhex(enc["wrapped_dek"]),
                        aad=b"rbd-dek")
                except Exception:
                    raise ValueError("wrong passphrase") from None
        if snapshot is not None:
            snap = self._hdr["snaps"].get(snapshot)
            if snap is None:
                raise ImageNotFound(f"no snapshot {snapshot!r}")
            self.snap_id = snap["id"]
            self._snap_size = snap["size"]
        elif not read_only and self._hdr.get("journaling") and \
                self._hdr.get("primary", True):
            # single-writer contract for journal sequencing: hold the
            # exclusive advisory lock for the handle's lifetime
            # (reference librbd exclusive-lock feature, required by
            # journaling) — a second writable open fails instead of
            # silently interleaving journal events
            import uuid
            cookie = uuid.uuid4().hex
            try:
                self.ioctx.lock_exclusive(_header_oid(name),
                                          "rbd_lock", cookie)
            except Exception as e:
                raise ValueError(
                    f"image {name!r} is locked by another writer "
                    f"(journaling requires a single writer): {e}"
                ) from None
            self._lock_cookie = cookie

    def _load_header(self):
        from ..osdc.librados import ObjectNotFound
        try:
            raw = self.ioctx.omap_get(_header_oid(self.name))["header"]
        except (KeyError, ObjectNotFound):
            raise ImageNotFound(self.name) from None
        self._hdr = json.loads(bytes(raw))
        self.layout = FileLayout(
            stripe_unit=self._hdr["stripe_unit"],
            stripe_count=self._hdr["stripe_count"],
            object_size=1 << self._hdr["order"])

    def _save_header(self):
        self.ioctx.omap_set(_header_oid(self.name), {
            "header": json.dumps(self._hdr).encode()})

    # -- metadata -----------------------------------------------------------
    def size(self) -> int:
        return self._snap_size if self.snap_id is not None \
            else self._hdr["size"]

    def stat(self) -> dict:
        return {"size": self.size(), "order": self._hdr["order"],
                "num_objs": -(-self._hdr["size"] //
                              self.layout.object_size),
                "snaps": sorted(self._hdr["snaps"])}

    # -- encryption --------------------------------------------------------
    def encryption_format(self, passphrase: str):
        """Turn encryption on (reference ``rbd encryption format``,
        LUKS-style).  Only an image with no data yet may be formatted
        — formatting does not re-encrypt existing bytes."""
        self._require_writable()
        if self._hdr.get("encryption") is not None:
            raise ValueError("image is already encrypted")
        if self._hdr.get("parent") is not None:
            raise ValueError("cannot format a clone")
        if self._hdr.get("journaling"):
            # the journal carries write payloads; pairing it with
            # at-rest encryption would leak every plaintext write
            raise ValueError(
                "encryption and journaling are mutually exclusive")
        if self._hdr.get("migration_source") is not None:
            # copy-up pulls PLAINTEXT source bytes into local
            # objects; mixing them with encrypted envelopes wedges
            # every later read
            raise ValueError(
                "finish the migration before formatting encryption")
        pre = f"rbd_data.{self.name}."
        if any(o.startswith(pre) and _is_data_suffix(o[len(pre):])
               for o in self.ioctx.list_objects()):
            raise ValueError(
                "image already has data; format before first write")
        import os as _os
        salt = _os.urandom(16)
        dek = _os.urandom(32)
        kek = _derive_kek(passphrase, salt)
        self._hdr["encryption"] = {
            "cipher": "aes-256-gcm",
            "salt": salt.hex(),
            "wrapped_dek": _seal(kek, dek, aad=b"rbd-dek").hex(),
        }
        self._save_header()
        self._dek = dek
        self._passphrase = passphrase

    def _require_unlocked(self):
        if self._locked:
            raise ValueError(
                f"image {self.name!r} is encrypted: passphrase "
                "required for data access")

    def _decrypt_obj(self, oid: str, raw: bytes) -> bytes:
        if self._dek is None or not raw:
            return raw
        try:
            return _unseal(self._dek, raw, aad=oid.encode())
        except Exception as e:
            raise ValueError(
                f"corrupt or tampered encrypted object {oid}: {e}"
            ) from None

    def _encrypt_obj(self, oid: str, plain: bytes) -> bytes:
        return _seal(self._dek, plain, aad=oid.encode())

    def _obj_patch(self, objno: int, payload: bytes, off: int):
        """Object-level write primitive: plain images write at the
        offset; encrypted images read-modify-write the whole envelope
        (GCM cannot be patched in place)."""
        oid = _data_oid(self.name, objno)
        if self._dek is None:
            self.ioctx.write(oid, payload, off)
            return
        from ..osdc.librados import ObjectNotFound
        try:
            raw = bytes(self.ioctx.read(oid))
        except ObjectNotFound:
            raw = b""
        cur = bytearray(self._decrypt_obj(oid, raw))
        end = off + len(payload)
        if len(cur) < end:
            cur.extend(b"\x00" * (end - len(cur)))
        cur[off:end] = payload
        self.ioctx.write_full(oid, self._encrypt_obj(oid, bytes(cur)))

    def resize(self, new_size: int):
        self._require_writable()
        self._journal_append({"op": "resize", "size": new_size})
        parent = self._hdr.get("parent")
        if parent is not None and new_size < parent["overlap"]:
            # shrinking a clone clamps the parent overlap: a later
            # grow must read zeros, never resurrect parent bytes
            # (reference librbd shrinks the parent overlap the same way)
            parent["overlap"] = new_size
        mig = self._hdr.get("migration_source")
        if mig is not None and new_size < mig.get("overlap",
                                                  new_size):
            mig["overlap"] = new_size
        old = self._hdr["size"]
        self._hdr["size"] = new_size
        self._save_header()
        if new_size < old:
            # drop whole objects past the new end (reference
            # librbd trim); partial tail objects keep their bytes but
            # reads clamp at size()
            first_dead = -(-new_size // self.layout.object_size)
            last = -(-old // self.layout.object_size)
            for objno in range(first_dead, last):
                self._cow_preserve(objno)   # snapshots keep the bytes
                try:
                    self.ioctx.remove(_data_oid(self.name, objno))
                except Exception:
                    pass
        if self._objmap_enabled():
            # re-persist at the new length: shrink drops the dead
            # objects' states, grow pads OM_NONE
            self._objmap_save(self._objmap_load())

    def close(self):
        if self._lock_cookie is not None:
            try:
                self.ioctx.unlock(_header_oid(self.name), "rbd_lock",
                                  self._lock_cookie)
            except Exception:
                pass
            self._lock_cookie = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _require_writable(self):
        if self.snap_id is not None:
            raise ValueError("image opened at a snapshot is read-only")
        if self._hdr.get("migrating"):
            raise ValueError(
                "image is mid-migration: writes go to the target")
        self._require_unlocked()
        if self._read_only and not getattr(self, "_replaying", False):
            raise ValueError("image opened read-only")
        if (self._hdr.get("journaling")
                or self._hdr.get("mirror_mode") == "snapshot") and \
                not self._hdr.get("primary", True) and \
                not getattr(self, "_replaying", False):
            raise ValueError(
                "image is non-primary (mirrored): writes only arrive "
                "via mirror replay; promote first")

    # -- journaling / mirroring ------------------------------------------
    # (reference src/librbd/journal/: every mutation is appended as a
    # journal event BEFORE being applied; rbd-mirror tails the journal.
    # Single-writer contract: a journaled primary image takes the
    # exclusive advisory lock at open — see __init__ — so the cached
    # head_seq below is sound and appends cannot interleave.)
    _TRIM_EVERY = 16

    def _journal_append(self, record: dict):
        if not self._hdr.get("journaling") or \
                getattr(self, "_replaying", False):
            return
        from ..osdc.librados import ObjectNotFound
        joid = _journal_oid(self.name)
        if getattr(self, "_journal_head", None) is None:
            # first append through this handle: one full read seeds
            # the cache (the exclusive lock guarantees nobody else
            # advances it); ONLY a missing object may default to
            # empty — any other error must propagate, or a transient
            # read failure would restart sequencing at 0 and the new
            # events would hide behind the mirror's commit position
            try:
                rows = self.ioctx.omap_get(joid)
            except ObjectNotFound:
                rows = {}
            self._journal_head = int(rows.get("head_seq", b"0"))
        self._journal_head += 1
        head = self._journal_head
        self.ioctx.omap_set(joid, {
            f"e{head:016d}": json.dumps(record).encode(),
            "head_seq": str(head).encode()})
        # trim entries every consumer has committed (the mirror daemon
        # reports its position into the same object; reference:
        # journal commit position + ObjectRecorder trim).  Amortized:
        # the trim pass re-reads the whole journal, so do it every
        # _TRIM_EVERY appends, not per write.
        if head % self._TRIM_EVERY == 0:
            try:
                rows = self.ioctx.omap_get(joid)
            except ObjectNotFound:
                return
            committed = int(rows.get("mirror_position", b"0"))
            dead = [k for k in rows
                    if k.startswith("e") and int(k[1:]) <= committed]
            if dead:
                self.ioctx.omap_rm_keys(joid, dead)

    def journal_entries(self, after: int = 0) -> list[tuple[int, dict]]:
        """Journal events with seq > after, in order."""
        try:
            rows = self.ioctx.omap_get(_journal_oid(self.name))
        except Exception:
            return []
        out = []
        for key, val in rows.items():
            if key.startswith("e") and int(key[1:]) > after:
                out.append((int(key[1:]), json.loads(bytes(val))))
        return sorted(out)

    def journal_commit(self, position: int):
        """Record the mirror consumer's commit position (trimming
        happens lazily on the next append)."""
        self.ioctx.omap_set(_journal_oid(self.name), {
            "mirror_position": str(position).encode()})

    def is_primary(self) -> bool:
        return bool(self._hdr.get("primary", True))

    def promote(self):
        """Make this side primary (failover; reference
        ``rbd mirror image promote``)."""
        self._load_header()
        self._hdr["primary"] = True
        self._save_header()

    def demote(self):
        """Make this side non-primary (planned failback)."""
        self._load_header()
        self._hdr["primary"] = False
        self._save_header()

    # -- snapshot-based mirroring ----------------------------------------
    # (reference src/tools/rbd_mirror/ snapshot mode + the mirror
    # snapshot schedule: the PRIMARY periodically stamps
    # ".mirror.primary.<id>" snapshots; the daemon ships the
    # object-map-assisted delta between consecutive mirror snapshots
    # and records its sync point back on the primary, which prunes
    # mirror snapshots older than the peer's sync point.)
    MIRROR_SNAP_PREFIX = ".mirror.primary."

    def mirror_mode(self) -> str | None:
        if self._hdr.get("mirror_mode") == "snapshot":
            return "snapshot"
        return "journal" if self._hdr.get("journaling") else None

    def mirror_snapshots(self) -> list[tuple[int, str]]:
        """Mirror snapshots as ordered (id, name).  Only names with a
        numeric sequence suffix qualify — the namespace is reserved
        (create_snap rejects user names under the prefix), but images
        imported from older clusters may carry strays; parsing them
        here would crash the mirror daemon's whole sync pass."""
        plen = len(self.MIRROR_SNAP_PREFIX)
        out = [(s["id"], nm) for nm, s in self._hdr["snaps"].items()
               if nm.startswith(self.MIRROR_SNAP_PREFIX)
               and nm[plen:].isdigit()]
        return sorted(out)

    def mirror_snapshot_create(self) -> str:
        """Primary-only: stamp a new mirror snapshot (what the
        reference's snapshot schedule does on its cadence), then prune
        mirror snapshots the peer has already synced past.

        The name's sequence number continues from the highest existing
        MIRROR snapshot name — NOT from the local snap_seq, which
        diverges across the two clusters (user snapshots advance it on
        the primary only; a promoted secondary would otherwise collide
        with a name it imported)."""
        if self.mirror_mode() != "snapshot":
            raise ValueError("image is not in snapshot mirror mode")
        self._require_writable()
        plen = len(self.MIRROR_SNAP_PREFIX)
        nums = [int(nm[plen:]) for _, nm in self.mirror_snapshots()]
        # monotonic even when older stamps were removed: reusing a
        # number would alias a NEW delta under a name the peer already
        # synced (silent divergence), so the header keeps the floor
        nxt = max([self._hdr.get("mirror_snap_seq", 0), *nums]) + 1
        self._hdr["mirror_snap_seq"] = nxt
        name = f"{self.MIRROR_SNAP_PREFIX}{nxt}"
        self.create_snap(name, _mirror_internal=True)  # persists header
        self._prune_mirror_snapshots()
        return name

    def mirror_snap_committed(self) -> int:
        """Highest mirror-snapshot id the peer reports fully synced."""
        try:
            rows = self.ioctx.omap_get(_journal_oid(self.name))
        except Exception:
            return 0
        return int(rows.get("mirror_snap_synced", b"0"))

    def mirror_snap_commit(self, snap_id: int):
        """Peer-side sync acknowledgement (the daemon writes this on
        the REMOTE image — the analog of journal_commit)."""
        self.ioctx.omap_set(_journal_oid(self.name), {
            "mirror_snap_synced": str(snap_id).encode()})

    def _prune_mirror_snapshots(self):
        """Drop mirror snapshots STRICTLY older than the peer's sync
        point: the synced one stays — it is the peer's next diff
        base — and unsynced ones must survive or the delta chain
        breaks."""
        committed = self.mirror_snap_committed()
        for sid, name in self.mirror_snapshots():
            if sid < committed:
                self.remove_snap(name)

    # -- object map / fast-diff --------------------------------------------
    # (reference src/librbd/object_map/ + the fast-diff feature: one
    # state byte per data object; the head map tracks what exists and
    # what was written since the last snapshot, each snapshot freezes
    # a copy.  export-diff consults the maps instead of scanning every
    # data object.)
    def _objmap_enabled(self) -> bool:
        return bool(self._hdr.get("object_map"))

    def _objmap_nobj(self, size: int | None = None) -> int:
        s = self._hdr["size"] if size is None else size
        return -(-s // self.layout.object_size)

    def _objmap_load(self, snap_id: int | None = None,
                     nobj: int | None = None) -> bytearray:
        """The map, padded/truncated to `nobj` entries (missing map
        object ⇒ all OM_NONE: a fresh image has no data objects)."""
        n = self._objmap_nobj() if nobj is None else nobj
        try:
            raw = bytes(self.ioctx.read(
                _objmap_oid(self.name, snap_id)))
        except Exception:       # noqa: BLE001 — absent map
            raw = b""
        m = bytearray(raw[:n])
        m.extend(b"\x00" * (n - len(m)))
        return m

    def _objmap_save(self, m: bytearray,
                     snap_id: int | None = None):
        self.ioctx.write_full(_objmap_oid(self.name, snap_id),
                              bytes(m))

    def _objmap_mark(self, objnos, state: int = OM_DIRTY):
        if not self._objmap_enabled():
            return
        m = self._objmap_load()
        changed = False
        for objno in objnos:
            if objno < len(m) and m[objno] != state:
                m[objno] = state
                changed = True
        if changed:
            self._objmap_save(m)

    def _fast_diff_objects(self, from_snap: str | None) -> set | None:
        """Objects possibly changed between `from_snap` and this
        handle's view — the union of every intervening map's dirty
        set plus existence flips; → None when the maps can't answer
        (feature off, or a full export of a clone whose unwritten
        objects are parent-backed and absent from the map)."""
        if not self._objmap_enabled():
            return None
        if self._hdr.get("migration_source") is not None:
            # uncopied objects are readable but absent from the map
            return None
        if from_snap is None:
            # a full export of parent-backed data can't come from the
            # maps: unwritten clone objects are OM_NONE yet readable.
            # For a snapshot handle, what matters is whether the image
            # had a parent AT SNAP TIME (flatten may have popped the
            # header's parent since) — recorded per snap; absent field
            # (pre-feature snaps) is treated as "had one": fallback
            # scan is slow but never wrong
            if self._hdr.get("parent") is not None:
                return None
            if self.snap_id is not None:
                snap = next(
                    (s for s in self._hdr["snaps"].values()
                     if s["id"] == self.snap_id), {})
                if snap.get("has_parent", True):
                    return None
        from_id = (self._hdr["snaps"][from_snap]["id"]
                   if from_snap else 0)
        end_id = self.snap_id            # None ⇒ head
        nobj = self._objmap_nobj(self.size())
        # maps strictly after from_id up to (and including) the end
        mid_ids = sorted(
            s["id"] for s in self._hdr["snaps"].values()
            if s["id"] > from_id
            and (end_id is None or s["id"] <= end_id))
        maps = [self._objmap_load(sid, nobj) for sid in mid_ids]
        end_map = (self._objmap_load(None, nobj) if end_id is None
                   else self._objmap_load(end_id, nobj))
        if end_id is None:
            maps.append(end_map)
        cand = set()
        for m in maps:
            cand.update(i for i, v in enumerate(m) if v == OM_DIRTY)
        if from_snap is not None:
            base_map = self._objmap_load(from_id, nobj)
            cand.update(i for i in range(nobj)
                        if (end_map[i] == OM_NONE)
                        != (base_map[i] == OM_NONE))
        else:
            cand.update(i for i, v in enumerate(end_map)
                        if v != OM_NONE)
        return cand

    # -- snapshots -----------------------------------------------------------
    def create_snap(self, snap_name: str, *, _mirror_internal=False):
        self._require_writable()
        if (snap_name.startswith(self.MIRROR_SNAP_PREFIX)
                and not _mirror_internal):
            # reserved namespace: a user snapshot here would either
            # collide with a future stamp number or (non-numeric
            # suffix) confuse peers scanning the prefix
            raise ValueError(
                f"snapshot names under {self.MIRROR_SNAP_PREFIX!r} "
                "are reserved for snapshot-mode mirroring")
        if snap_name in self._hdr["snaps"]:
            raise ValueError(f"snapshot {snap_name!r} exists")
        self._journal_append({"op": "snap_create", "name": snap_name})
        sid = self._hdr["snap_seq"] + 1
        m = None
        if self._objmap_enabled():
            # freeze the map under the NEW id BEFORE the header
            # registers the snap: a crash in between leaves only an
            # orphan map object (the retry overwrites it) — the other
            # order would register a snap whose map loads as all-NONE
            # and silently drop objects from incrementals
            m = self._objmap_load()
            self._objmap_save(m, sid)
        self._hdr["snap_seq"] = sid
        self._hdr["snaps"][snap_name] = {
            "id": sid, "size": self._hdr["size"],
            # fast-diff needs to know whether this snap's view has
            # parent-backed bytes the object map can't see
            "has_parent": self._hdr.get("parent") is not None}
        self._save_header()
        if m is not None:
            # clean the head LAST: a crash before this leaves extra
            # dirty bits (conservative — more diff reads, never fewer)
            for i, v in enumerate(m):
                if v == OM_DIRTY:
                    m[i] = OM_CLEAN
            self._objmap_save(m)

    def protect_snap(self, snap_name: str):
        """Required before cloning (reference snap protect)."""
        self._require_writable()
        snap = self._hdr["snaps"].get(snap_name)
        if snap is None:
            raise ImageNotFound(f"no snapshot {snap_name!r}")
        snap["protected"] = True
        self._save_header()

    def unprotect_snap(self, snap_name: str):
        self._require_writable()
        snap = self._hdr["snaps"].get(snap_name)
        if snap is None:
            raise ImageNotFound(f"no snapshot {snap_name!r}")
        if snap.get("children"):
            raise ValueError(
                f"snapshot has children: {snap['children']} "
                "(flatten them first)")
        snap["protected"] = False
        self._save_header()

    def remove_snap(self, snap_name: str):
        self._require_writable()
        if snap_name not in self._hdr["snaps"]:
            raise ImageNotFound(f"no snapshot {snap_name!r}")
        if self._hdr["snaps"][snap_name].get("protected"):
            raise ValueError(f"snapshot {snap_name!r} is protected")
        self._journal_append({"op": "snap_remove", "name": snap_name})
        gone = self._hdr["snaps"].pop(snap_name)
        self._save_header()
        if self._objmap_enabled():
            # merge the removed snap's DIRTY bits into the next newer
            # map (or the head map): its interval's changes must stay
            # visible to fast-diff, or an incremental spanning the
            # removed snap silently loses them (reference
            # object_map::SnapshotRemoveRequest does the same merge)
            removed = self._objmap_load(gone["id"],
                                        self._objmap_nobj(
                                            gone["size"]))
            newer = sorted(
                (s["id"], s["size"])
                for s in self._hdr["snaps"].values()
                if s["id"] > gone["id"])
            tgt_sid = newer[0][0] if newer else None
            # load the target at ITS OWN length (snap maps keep their
            # snap-time size; the head map the current size)
            tgt = self._objmap_load(
                tgt_sid,
                self._objmap_nobj(newer[0][1]) if newer else None)
            changed = False
            for i in range(min(len(removed), len(tgt))):
                if removed[i] == OM_DIRTY and tgt[i] == OM_CLEAN:
                    tgt[i] = OM_DIRTY
                    changed = True
            if changed:
                self._objmap_save(tgt, tgt_sid)
            try:
                self.ioctx.remove(_objmap_oid(self.name, gone["id"]))
            except Exception:       # noqa: BLE001 — map may be absent
                pass
        self._gc_clones()

    def _gc_clones(self):
        """Collect clone objects no remaining snapshot resolves to.
        Mirrors _read_object_at_snap exactly: each snap uses the
        OLDEST clone with id >= its own; every other clone is garbage
        (reference: the OSD's snap trimmer removing unreferenced
        clones)."""
        snap_ids = sorted(s["id"]
                          for s in self._hdr["snaps"].values())
        prefix = f"rbd_data.{self.name}."
        clones: dict[str, list[int]] = {}
        for o in self.ioctx.list_objects():
            if o.startswith(prefix) and "@" in o and \
                    _is_data_suffix(o[len(prefix):]):
                base, _, cid = o.rpartition("@")
                clones.setdefault(base, []).append(int(cid))
        for base, cids in clones.items():
            needed = set()
            for sid in snap_ids:
                cand = min((c for c in cids if c >= sid),
                           default=None)
                if cand is not None:
                    needed.add(cand)
            for c in cids:
                if c not in needed:
                    self.ioctx.remove(f"{base}@{c}")

    def list_snaps(self) -> list[dict]:
        return [{"name": n, **s}
                for n, s in sorted(self._hdr["snaps"].items())]

    def _cow_preserve(self, objno: int):
        """Before the first overwrite after a snapshot, preserve the
        object's current bytes for every snap that hasn't got a clone
        yet (reference: the OSD clones via SnapContext; same effect)."""
        snaps = self._hdr["snaps"]
        if not snaps:
            return
        oid = _data_oid(self.name, objno)
        try:
            cloned = int(bytes(self.ioctx.getxattr(oid,
                                                   "cloned_upto")))
        except Exception:
            cloned = 0
        newest = max(s["id"] for s in snaps.values())
        if cloned >= newest:
            return
        try:
            cur = self.ioctx.read(oid)
        except Exception:
            cur = None     # sparse: snapshot reads fall back to zeros
        if cur is not None:
            self.ioctx.write_full(f"{oid}@{newest}", cur)
        self.ioctx.setxattr(oid, "cloned_upto", str(newest).encode())

    def _read_object_at_snap(self, objno: int) -> bytes:
        """Resolve an object's bytes as of self.snap_id: the oldest
        clone whose id >= snap_id, else the head object if it was
        never overwritten past snap_id."""
        oid = _data_oid(self.name, objno)
        clones = []
        prefix = f"{oid}@"
        for o in self.ioctx.list_objects():
            if o.startswith(prefix):
                clones.append(int(o[len(prefix):]))
        for cid in sorted(clones):
            if cid >= self.snap_id:
                try:
                    return self.ioctx.read(f"{oid}@{cid}")
                except Exception:
                    return b""
        try:
            cloned = int(bytes(self.ioctx.getxattr(oid,
                                                   "cloned_upto")))
        except Exception:
            cloned = 0
        if cloned >= self.snap_id:
            # head was overwritten after the snap but the pre-snap
            # state was sparse (no clone written): zeros
            return b""
        try:
            return self.ioctx.read(oid)
        except Exception:
            return b""

    # -- clone / parent fall-through --------------------------------------
    def _parent_covers(self, objno: int) -> bool:
        """Cheap (no I/O) test: does the parent overlap back any byte
        of this child object?"""
        parent = self._hdr.get("parent")
        if parent is None:
            return False
        lay = self.layout
        sc = lay.stripe_count
        su = lay.stripe_unit
        su_per_object = lay.object_size // su
        # first logical byte an object holds: its first stripe unit
        objectsetno, stripepos = objno // sc, objno % sc
        first_stripeno = objectsetno * su_per_object
        first_logical = (first_stripeno * sc + stripepos) * su
        return first_logical < parent["overlap"]

    def _parent_bytes(self, objno: int) -> bytes | None:
        """The parent@snap bytes backing this child object, laid out
        in the OBJECT's internal order, or None when no parent covers
        it (reference: reads below the overlap fall through the parent
        chain).  Stripe-aware: with stripe_count > 1 an object holds
        interleaved stripe units from non-contiguous logical ranges,
        so each unit is fetched at its own logical offset."""
        if not self._parent_covers(objno):
            return None
        parent = self._hdr.get("parent")
        lay = self.layout
        sc, su = lay.stripe_count, lay.stripe_unit
        su_per_object = lay.object_size // su
        objectsetno, stripepos = objno // sc, objno % sc
        out = bytearray()
        with Image(self.ioctx, parent["image"],
                   snapshot=parent["snap"]) as p:
            for u in range(su_per_object):
                stripeno = objectsetno * su_per_object + u
                logical = (stripeno * sc + stripepos) * su
                if logical >= parent["overlap"]:
                    break
                n = min(su, parent["overlap"] - logical)
                piece = p.read(logical, n)
                out.extend(piece)
                if len(piece) < su:
                    break
        return bytes(out) if out else None

    # -- migration fall-through -------------------------------------------
    def _migration_bytes(self, objno: int) -> bytes | None:
        """Plaintext bytes of a not-yet-copied object from the
        migration source (reads fall through like a clone's parent)."""
        mig = self._hdr.get("migration_source")
        if mig is None:
            return None
        base = objno * self.layout.object_size
        ov = mig.get("overlap")
        if ov is not None and base >= ov:
            return None         # beyond the clamped overlap: zeros
        src_io = getattr(self, "_mig_io", None)
        if src_io is None:
            src_io = self._mig_io = self.ioctx.rados.open_ioctx(
                mig["pool"])
        try:
            raw = bytes(src_io.read(_data_oid(mig["image"], objno)))
        except Exception:       # noqa: BLE001 — absent or transient
            return None
        if ov is not None and base + len(raw) > ov:
            raw = raw[:ov - base]
        return raw

    def _migration_copy_up(self, objno: int):
        """First write to an uncopied object pulls the source bytes
        in first (the copyup discipline, reference deep-copyup)."""
        if self._hdr.get("migration_source") is None:
            return
        if self._object_exists(objno):
            return
        base = self._migration_bytes(objno)
        if base:
            self.ioctx.write_full(_data_oid(self.name, objno), base)

    def _object_exists(self, objno: int) -> bool:
        from ..osdc.librados import ObjectNotFound
        try:
            self.ioctx.stat(_data_oid(self.name, objno))
            return True
        except ObjectNotFound:
            return False

    def _copy_up(self, objno: int) -> bool:
        """First write to a parent-backed object copies the parent
        bytes into the child first (reference copyup).  → True iff
        the child owns the object afterwards (flatten uses this to
        build the object map without re-statting everything)."""
        if self._hdr.get("parent") is None:
            return self._object_exists(objno)
        oid = _data_oid(self.name, objno)
        from ..osdc.librados import ObjectNotFound
        try:
            self.ioctx.stat(oid)
            return True         # child already owns this object
        except ObjectNotFound:
            # only a definitive "absent" may fall through to the
            # copyup write: a transient error on an object the child
            # already wrote must propagate, or the write_full below
            # would clobber the child's data with stale parent bytes
            pass
        base = self._parent_bytes(objno)
        if base:
            self.ioctx.write_full(oid, base)
            return True
        return False

    def flatten(self):
        """Copy all parent-backed data into the child and detach it
        (reference ``rbd flatten``)."""
        self._require_writable()
        parent = self._hdr.get("parent")
        if parent is None:
            return
        # exact object set: with striping, an object's logical bytes
        # are interleaved — derive the covered objects from the layout
        nobj = 1 + max(
            (e.object_no for e in
             file_to_extents(self.layout, 0, parent["overlap"])),
            default=-1)
        owned = {objno for objno in range(nobj)
                 if self._copy_up(objno)}
        if self._objmap_enabled():
            # the copied-up objects now hold the image's only copy of
            # the parent bytes: they must enter the object map, or the
            # first post-flatten export-diff would skip them
            self._objmap_mark(owned)
        with Image(self.ioctx, parent["image"]) as p:
            snap = p._hdr["snaps"].get(parent["snap"])
            if snap is not None:
                kids = snap.get("children", [])
                if self.name in kids:
                    kids.remove(self.name)
                p._save_header()
        self._hdr.pop("parent", None)
        self._save_header()

    # -- incremental diff (reference rbd export-diff / import-diff) -----
    def export_diff(self, from_snap: str | None = None) -> dict:
        """Changed extents since `from_snap` (None ⇒ everything) up
        to this handle's view (a snapshot handle diffs to that snap,
        a head handle to the current data) — the transport behind
        incremental backup/mirroring (reference ``rbd export-diff``).
        Extent granularity: differing byte ranges within each object.
        With the object-map feature the candidate objects come from
        the maps (fast-diff): unchanged objects are SKIPPED without
        any data read — the map lookup replaces the full scan."""
        size = self.size()
        base = None
        if from_snap is not None:
            if from_snap not in self._hdr["snaps"]:
                raise ImageNotFound(f"no snapshot {from_snap!r}")
            base = Image(self.ioctx, self.name, snapshot=from_snap,
                         passphrase=self._passphrase)
        candidates = self._fast_diff_objects(from_snap)
        try:
            extents = []
            step = self.layout.object_size
            off = 0
            chunk = 4096
            while off < size:
                if candidates is not None and \
                        (off // step) not in candidates:
                    off += step
                    continue
                n = min(step, size - off)
                new = self.read(off, n)
                if base is not None:
                    old = base.read(off, n)
                    if len(old) < n:
                        old += b"\x00" * (n - len(old))
                else:
                    old = b"\x00" * n
                if new != old:
                    # narrow by C-speed chunk comparisons, then
                    # byte-trim only inside the boundary chunks — a
                    # per-byte Python walk over a 4 MiB object costs
                    # seconds per changed object
                    lo = 0
                    while lo < n and \
                            new[lo:lo + chunk] == old[lo:lo + chunk]:
                        lo += chunk
                    hi = n
                    while hi > lo and new[max(hi - chunk, lo):hi] == \
                            old[max(hi - chunk, lo):hi]:
                        hi -= chunk
                    hi = min(hi, n)
                    while lo < hi and new[lo] == old[lo]:
                        lo += 1
                    while hi > lo and new[hi - 1] == old[hi - 1]:
                        hi -= 1
                    extents.append({"off": off + lo,
                                    "data": new[lo:hi].hex()})
                off += n
        finally:
            if base is not None:
                base.close()
        return {"image": self.name, "size": size,
                "from_snap": from_snap,
                "to_snap": next(
                    (nm for nm, sn in self._hdr["snaps"].items()
                     if sn["id"] == self.snap_id), None),
                "extents": extents}

    def import_diff(self, diff: dict):
        """Apply an exported diff (reference ``rbd import-diff``):
        validate the base snapshot, resize, write each extent, then
        stamp the end snapshot — the chain discipline that makes
        out-of-order incrementals fail loudly instead of silently
        corrupting the restore."""
        self._require_writable()
        if diff.get("from_snap") and \
                diff["from_snap"] not in self._hdr["snaps"]:
            raise ValueError(
                f"diff is based on snapshot {diff['from_snap']!r} "
                "which this image does not have — apply the earlier "
                "diffs first")
        if diff["size"] != self._hdr["size"]:
            self.resize(diff["size"])
        for ext in diff["extents"]:
            self.write(ext["off"], bytes.fromhex(ext["data"]))
        to_snap = diff.get("to_snap")
        if to_snap and to_snap not in self._hdr["snaps"]:
            # stamp the chain endpoint so the NEXT incremental's
            # from_snap check passes (reference import-diff creates
            # the end snap after applying).  _mirror_internal: in
            # snapshot-mode sync the endpoint IS a reserved
            # .mirror.primary.N name the secondary must reproduce
            self.create_snap(to_snap, _mirror_internal=True)

    # -- data path ------------------------------------------------------------
    def write(self, offset: int, data: bytes) -> int:
        self._require_writable()
        if offset + len(data) > self._hdr["size"]:
            raise ValueError("write past end of image")
        self._journal_append({"op": "write", "off": offset,
                              "data": data.hex()})
        exts = file_to_extents(self.layout, offset, len(data))
        # mark BEFORE the data writes (reference object-map ordering):
        # a mid-loop failure then leaves objects dirty-but-unwritten
        # (harmless extra diff reads), never written-but-clean (lost
        # from the next incremental)
        self._objmap_mark({e.object_no for e in exts})
        for ext in exts:
            self._copy_up(ext.object_no)
            self._migration_copy_up(ext.object_no)
            self._cow_preserve(ext.object_no)
            lo = ext.logical_offset - offset
            self._obj_patch(ext.object_no,
                            data[lo:lo + ext.length], ext.offset)
        return len(data)

    def read(self, offset: int, length: int) -> bytes:
        self._require_unlocked()
        end = min(offset + length, self.size())
        if end <= offset:
            return b""
        length = end - offset
        out = bytearray(length)
        for ext in file_to_extents(self.layout, offset, length):
            raw = True      # raw object bytes (decrypt if encrypted)
            if self.snap_id is not None:
                obj = self._read_object_at_snap(ext.object_no)
                if not obj:
                    obj = self._parent_bytes(ext.object_no) or b""
                    raw = False     # parent returns plaintext
            else:
                try:
                    obj = self.ioctx.read(
                        _data_oid(self.name, ext.object_no))
                except Exception:
                    obj = (self._parent_bytes(ext.object_no)
                           or self._migration_bytes(ext.object_no)
                           or b"")
                    raw = False     # source image returns plaintext
            if raw and self._dek is not None:
                obj = self._decrypt_obj(
                    _data_oid(self.name, ext.object_no), bytes(obj))
            piece = obj[ext.offset:ext.offset + ext.length]
            lo = ext.logical_offset - offset
            out[lo:lo + len(piece)] = piece
        return bytes(out)

    def discard(self, offset: int, length: int):
        """Zero a range (whole-object removes when aligned)."""
        self._require_writable()
        self._journal_append({"op": "discard", "off": offset,
                              "len": length})
        from ..osdc.librados import ObjectNotFound
        exts = file_to_extents(self.layout, offset, length)
        # conservative ordering: everything touched goes DIRTY first;
        # only a CONFIRMED removal (ok or already-absent) may demote
        # to NONE afterwards — a swallowed transient error must not
        # leave live data invisible to fast-diff
        self._objmap_mark({e.object_no for e in exts})
        gone = set()
        for ext in exts:
            oid = _data_oid(self.name, ext.object_no)
            parent_backed = (
                self._parent_covers(ext.object_no)
                or self._hdr.get("migration_source") is not None)
            if ext.offset == 0 and \
                    ext.length == self.layout.object_size and \
                    not parent_backed:
                self._cow_preserve(ext.object_no)
                try:
                    self.ioctx.remove(oid)
                    gone.add(ext.object_no)
                except ObjectNotFound:
                    gone.add(ext.object_no)
                except Exception:       # noqa: BLE001 — stays DIRTY
                    pass
            else:
                # parent-/source-backed objects must be ZEROED, not
                # removed — removal would resurrect the backing bytes
                if parent_backed:
                    self._copy_up(ext.object_no)
                    self._migration_copy_up(ext.object_no)
                self._cow_preserve(ext.object_no)
                self._obj_patch(ext.object_no,
                                b"\x00" * ext.length, ext.offset)
        self._objmap_mark(gone, OM_NONE)

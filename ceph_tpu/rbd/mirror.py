"""rbd-mirror — journal-based asynchronous image replication.

Reference behavior re-created (``src/tools/rbd_mirror/``,
``src/librbd/journal/``; SURVEY.md §3.9 "rbd-mirror"): a daemon
running near the SECONDARY cluster discovers journaled primary images
in the remote (primary) pool, bootstraps a local non-primary copy, and
tails each image's journal — replaying write/discard/resize/snapshot
events in order onto the local image and reporting its commit position
back into the remote journal so the primary can trim.  Failover =
stop replaying + ``promote()`` the local image; the non-primary write
guard (``Image._require_writable``) enforces the single-writer
contract the reference enforces via exclusive-lock + mirror state.

Direction note: like the reference, replication is PULL — the daemon
holds a client to both clusters; the primary never pushes.
"""

from __future__ import annotations

import threading
import time

from .image import RBD, Image, ImageNotFound, _journal_oid


class MirrorDaemon:
    """Replays journaled images from a remote (primary) pool into a
    local pool (reference ``rbd_mirror::ImageReplayer``)."""

    def __init__(self, remote_ioctx, local_ioctx, *,
                 interval: float = 0.1):
        self.remote = remote_ioctx
        self.local = local_ioctx
        self.interval = interval
        self.rbd = RBD()
        self.positions: dict[str, int] = {}   # image → replayed seq
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.errors: list[str] = []

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "MirrorDaemon":
        self._thread = threading.Thread(target=self._run,
                                        name="rbd-mirror", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.sync_once()
            except Exception as e:      # noqa: BLE001 — a cluster
                # hiccup must not kill the replayer; next tick retries
                self.errors.append(repr(e))

    # -- one replication pass ---------------------------------------------
    def sync_once(self) -> int:
        """Bootstrap + replicate every mirrored primary remote image
        (journal replay or snapshot-delta sync per image mode);
        returns the number of events/deltas applied."""
        applied = 0
        for name in self.rbd.list(self.remote):
            try:
                rimg = Image(self.remote, name, read_only=True)
            except ImageNotFound:
                continue
            if not rimg.is_primary():
                continue
            mode = rimg.mirror_mode()
            if mode == "snapshot":
                applied += self._sync_snapshot_image(name, rimg)
            elif mode == "journal":
                applied += self._replay_image(name, rimg)
        return applied

    def _resync_local(self, name: str):
        """Drop the local non-primary copy so the next pass
        re-bootstraps it in full (reference `rbd mirror image
        resync`)."""
        try:
            self.rbd.remove(self.local, name)
        except Exception as e:      # noqa: BLE001 — leave it for the
            self.errors.append(     # operator if removal also fails
                f"resync removal of {name!r} failed: {e!r}")
        self.positions.pop(name, None)

    # -- snapshot-mode sync (reference rbd_mirror snapshot replayer) ------
    def _sync_snapshot_image(self, name: str, rimg: Image) -> int:
        """Ship the delta between consecutive primary mirror
        snapshots: for each remote mirror snapshot the local copy
        lacks, export-diff from the previous mirror snapshot (the
        object-map fast-diff path skips untouched objects), import it
        locally (which stamps the matching snapshot), and acknowledge
        the sync point on the primary so it can prune."""
        msnaps = rimg.mirror_snapshots()
        if not msnaps:
            return 0
        try:
            limg = Image(self.local, name, read_only=True)
        except ImageNotFound:
            self.rbd.create(self.local, name, rimg._hdr["size"],
                            order=rimg._hdr["order"],
                            stripe_unit=rimg._hdr["stripe_unit"],
                            stripe_count=rimg._hdr["stripe_count"],
                            mirror_snapshot=True, primary=False)
            limg = Image(self.local, name, read_only=True)
        if limg.is_primary():
            self.errors.append(f"split-brain on image {name!r}")
            return 0
        # progress is ordered by mirror-snapshot NAME number (the
        # primary's stamp sequence, identical on both sides); local
        # snap ids diverge and older local stamps get pruned, so
        # neither can order the sync.  Everything <= the newest local
        # stamp is already applied — re-importing an older delta would
        # REGRESS the secondary's data.
        plen = len(Image.MIRROR_SNAP_PREFIX)
        local_nums = [int(nm[plen:])
                      for _, nm in limg.mirror_snapshots()]
        synced_upto = max(local_nums, default=-1)
        base = (f"{Image.MIRROR_SNAP_PREFIX}{synced_upto}"
                if synced_upto >= 0 else None)
        applied = 0
        for sid, sname in msnaps:
            if int(sname[plen:]) <= synced_upto:
                continue
            try:
                src = Image(self.remote, name, snapshot=sname,
                            read_only=True)
                try:
                    diff = src.export_diff(from_snap=base)
                finally:
                    src.close()
            except ImageNotFound as e:
                # re-read the primary's snap table: if our diff BASE
                # is truly gone there the chain cannot re-resolve on
                # its own — resync from scratch (the reference's
                # `rbd mirror image resync`: drop the local copy and
                # re-bootstrap); anything else is a transient race
                # with a concurrent stamp/prune — retry next pass
                base_gone = False
                if base is not None:
                    try:
                        with Image(self.remote, name,
                                   read_only=True) as fresh:
                            base_gone = base not in \
                                fresh._hdr["snaps"]
                    except ImageNotFound:
                        pass
                if base_gone:
                    self.errors.append(
                        f"mirror chain broken for {name!r} (base "
                        f"{base!r} removed on primary): resyncing")
                    self._resync_local(name)
                else:
                    self.errors.append(
                        f"snapshot chain moved on primary for "
                        f"{name!r}: {e}")
                return applied
            limg._replaying = True
            try:
                limg.import_diff(diff)   # stamps `sname` locally
            finally:
                limg._replaying = False
            rimg.mirror_snap_commit(sid)
            self.positions[name] = sid
            base = sname
            synced_upto = int(sname[plen:])
            applied += 1
        if applied and base is not None:
            # secondary-side prune: older local mirror snapshots (and
            # their COW clones) would otherwise accumulate one per
            # cadence tick forever; only the latest is ever needed as
            # the next import's from_snap base (the reference daemon
            # prunes non-primary mirror snapshots the same way)
            limg._replaying = True
            try:
                for _lsid, lname in limg.mirror_snapshots():
                    if lname != base:
                        limg.remove_snap(lname)
            finally:
                limg._replaying = False
        return applied

    def _bootstrap(self, name: str, rimg: Image) -> Image:
        """Ensure the local non-primary copy exists (reference
        bootstrap: full image SYNC — copy current bytes + snapshot
        table — then start replay from the journal position observed
        BEFORE the copy, so pre-sync history is never re-applied;
        events racing the copy replay harmlessly since the replay ops
        are idempotent)."""
        try:
            return Image(self.local, name, read_only=True)
        except ImageNotFound:
            pass
        # observe the journal head first: everything <= head is (or
        # will be) reflected in the bytes we copy below
        entries = rimg.journal_entries()
        head = entries[-1][0] if entries else 0
        self.rbd.create(self.local, name, rimg._hdr["size"],
                        order=rimg._hdr["order"],
                        stripe_unit=rimg._hdr["stripe_unit"],
                        stripe_count=rimg._hdr["stripe_count"],
                        journaling=True, primary=False)
        limg = Image(self.local, name, read_only=True)
        # snapshot table + sizes come with the sync (reference: the
        # bootstrap's snapshot sync)
        limg._hdr["snaps"] = dict(rimg._hdr["snaps"])
        limg._hdr["snap_seq"] = rimg._hdr["snap_seq"]
        limg._save_header()
        # full object copy: heads AND snap clones
        prefix = f"rbd_data.{name}."
        for o in self.remote.list_objects():
            if o.startswith(prefix):
                self.local.write_full(o, self.remote.read(o))
                try:
                    cl = self.remote.getxattr(o, "cloned_upto")
                    self.local.setxattr(o, "cloned_upto", bytes(cl))
                except Exception:
                    pass
        self.local.omap_set(_journal_oid(name), {
            "replayed": str(head).encode()})
        self.positions[name] = head
        return limg

    def _replay_image(self, name: str, rimg: Image) -> int:
        limg = self._bootstrap(name, rimg)
        if limg.is_primary():
            # split-brain: both sides primary (reference raises the
            # same health error and refuses to replay)
            self.errors.append(f"split-brain on image {name!r}")
            return 0
        pos = self.positions.get(name)
        if pos is None:
            # resume from the position persisted locally (daemon
            # restart must not re-apply (non-idempotent) snap events)
            try:
                rows = self.local.omap_get(_journal_oid(name))
                pos = int(rows.get("replayed", b"0"))
            except Exception:
                pos = 0
        applied = 0
        for seq, rec in rimg.journal_entries(after=pos):
            self._apply(limg, rec)
            pos = seq
            applied += 1
            # persist position per EVENT: a crash between events must
            # not re-apply the ones already replayed (reference:
            # journal commit position advanced per entry)
            self.positions[name] = pos
            self.local.omap_set(_journal_oid(name), {
                "replayed": str(pos).encode()})
        if applied:
            rimg.journal_commit(pos)      # lets the primary trim
        else:
            self.positions[name] = pos
        return applied

    def _apply(self, limg: Image, rec: dict):
        """Replay one event.  Each arm is IDEMPOTENT — bootstrap races
        and crash-replay overlap mean an event can be applied onto a
        state that already reflects it."""
        limg._replaying = True
        try:
            op = rec["op"]
            if op == "write":
                data = bytes.fromhex(rec["data"])
                end = rec["off"] + len(data)
                if end > limg._hdr["size"]:
                    # write preceded a shrink we'll replay later (or
                    # raced the bootstrap's size snapshot): grow now,
                    # the upcoming resize event restores the final size
                    limg._hdr["size"] = end
                    limg._save_header()
                limg.write(rec["off"], data)
            elif op == "discard":
                limg.discard(rec["off"], rec["len"])
            elif op == "resize":
                limg.resize(rec["size"])
            elif op == "snap_create":
                if rec["name"] not in limg._hdr["snaps"]:
                    # faithful replay: reproduce the source snapshot
                    # even if its name sits in a reserved namespace
                    limg.create_snap(rec["name"],
                                     _mirror_internal=True)
            elif op == "snap_remove":
                if rec["name"] in limg._hdr["snaps"]:
                    limg.remove_snap(rec["name"])
            else:
                self.errors.append(f"unknown journal op {op!r}")
        finally:
            limg._replaying = False


def promote(ioctx, name: str):
    """``rbd mirror image promote`` (failover to this cluster)."""
    Image(ioctx, name, read_only=True).promote()


def demote(ioctx, name: str):
    """``rbd mirror image demote``."""
    Image(ioctx, name, read_only=True).demote()

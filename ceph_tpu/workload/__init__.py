"""Open-loop SLO harness: seeded load generation, per-tenant SLO
tracking, and scenario scripts over the MiniCluster + RGW front door.
"""

from .generator import (S3_GET, S3_PUT, RBD_READ, RBD_WRITE, FS_READ,
                        FS_WRITE, ArrivalSchedule, LoadGenerator,
                        OpMix, OpRecord, TenantProfile, Throttled,
                        merge_profiles)
from .slo import SLOTracker
from .scenarios import (game_day_under_load, make_executor,
                        noisy_neighbor, publish_slo, ramp_to_collapse,
                        schedule_fingerprint, smoke, steady_state)

__all__ = [
    "S3_GET", "S3_PUT", "RBD_READ", "RBD_WRITE", "FS_READ",
    "FS_WRITE", "ArrivalSchedule", "LoadGenerator", "OpMix",
    "OpRecord", "TenantProfile", "Throttled", "merge_profiles",
    "SLOTracker", "game_day_under_load", "make_executor",
    "noisy_neighbor", "publish_slo", "ramp_to_collapse",
    "schedule_fingerprint", "smoke", "steady_state",
]

"""SLO tracking — per-tenant/per-op-class latency, windowed
quantiles, goodput vs offered load, time-in-violation.

Latencies land in the repo's log2 histograms
(`core.perf_counters.LogHistogram`, the reference PerfHistogram
shape) in **microseconds**, and quantiles come from the same
`hist_quantile` the mgr telemetry spine uses — so a number printed by
a scenario is bucket-for-bucket comparable with `ceph osd perf` /
exporter output.  Windowed p50/p99/p999 subtract periodic bucket
snapshots (counts are monotone, so window = now − snapshot(t−w)).

**Goodput** counts only ops that completed OK *and* under their SLO
target (throttled ops and SLO-busting stragglers are offered load
that produced no good work — the gap between the two curves is the
collapse signature).  **Violation accounting** integrates wall time
while a tracked (tenant, op-class)'s windowed p99 sits above target.

Thread-safe: one lock, taken briefly per record — the tracker rides
inside the load generator's worker pool.
"""

from __future__ import annotations

import threading
import time

from ..core.perf_counters import LogHistogram
from ..mgr.telemetry import hist_quantile

X_BUCKETS = 32          # log2 µs buckets: covers ns..hours


class _Lane:
    """One (tenant, op_class) stream."""

    __slots__ = ("hist", "snaps", "count", "ok", "throttled",
                 "errors", "good", "lat_sum", "in_violation",
                 "violation_s", "last_eval")

    def __init__(self):
        self.hist = LogHistogram(x_buckets=X_BUCKETS)
        self.snaps: list[tuple[float, list[int]]] = []
        self.count = 0
        self.ok = 0
        self.throttled = 0
        self.errors = 0
        self.good = 0           # ok AND within the SLO target
        self.lat_sum = 0.0
        self.in_violation = False
        self.violation_s = 0.0
        self.last_eval: float | None = None


class SLOTracker:
    """`slo_ms` maps op-class → p99 latency target in ms (`"*"` = any
    class).  `window_s` is the sliding-quantile horizon."""

    SNAP_INTERVAL_S = 0.25

    def __init__(self, slo_ms: dict[str, float] | None = None, *,
                 window_s: float = 5.0, clock=time.monotonic):
        self.slo_ms = dict(slo_ms or {})
        self.window_s = float(window_s)
        self.clock = clock
        self._lanes: dict[tuple[str, str], _Lane] = {}
        self._lock = threading.Lock()
        self._t0 = None
        self._offered = 0
        self._duration = 0.0

    # -- ingest ------------------------------------------------------------
    def start(self, *, t0: float | None = None, offered: int = 0,
              duration: float = 0.0):
        """Called by the generator at schedule start (optional for
        standalone use): anchors elapsed time and the offered-load
        denominator."""
        with self._lock:
            self._t0 = self.clock() if t0 is None else t0
            self._offered += int(offered)
            self._duration = max(self._duration, float(duration))

    def target_ms(self, op_class: str) -> float | None:
        t = self.slo_ms.get(op_class, self.slo_ms.get("*"))
        return float(t) if t is not None else None

    def record(self, tenant: str, op_class: str, latency_s: float,
               *, ok: bool = True, throttled: bool = False):
        now = self.clock()
        us = max(0.0, latency_s * 1e6)
        target = self.target_ms(op_class)
        with self._lock:
            lane = self._lanes.setdefault((tenant, op_class), _Lane())
            lane.hist.add(us)
            lane.count += 1
            lane.lat_sum += latency_s
            if ok:
                lane.ok += 1
                if target is None or latency_s * 1e3 <= target:
                    lane.good += 1
            elif throttled:
                lane.throttled += 1
            else:
                lane.errors += 1
            snaps = lane.snaps
            if not snaps or now - snaps[-1][0] \
                    >= self.SNAP_INTERVAL_S:
                snaps.append((now, list(lane.hist.data[0])))
                horizon = now - 2.0 * self.window_s
                while len(snaps) > 2 and snaps[1][0] < horizon:
                    snaps.pop(0)

    # -- quantiles ---------------------------------------------------------
    def _window_counts(self, lane: _Lane, now: float) -> list[int]:
        cur = lane.hist.data[0]
        base = None
        for t, counts in reversed(lane.snaps):
            if now - t >= self.window_s:
                base = counts
                break
        if base is None:
            return list(cur)        # younger than one window: lifetime
        return [c - b for c, b in zip(cur, base)]

    def quantiles(self, tenant: str, op_class: str,
                  windowed: bool = False) -> dict:
        """→ {"p50_ms", "p99_ms", "p999_ms"} (0s when no samples)."""
        with self._lock:
            lane = self._lanes.get((tenant, op_class))
            if lane is None:
                return {"p50_ms": 0.0, "p99_ms": 0.0, "p999_ms": 0.0}
            counts = (self._window_counts(lane, self.clock())
                      if windowed else lane.hist.data[0])
        return {f"p{q}".replace(".", "") + "_ms":
                hist_quantile(counts, float(f"0.{q}")) / 1e3
                for q in ("50", "99", "999")}

    # -- violation accounting ----------------------------------------------
    def evaluate(self, now: float | None = None) -> dict[str, bool]:
        """Tick the violation integrator: for every tracked lane with
        an SLO target, compare the windowed p99 against it and accrue
        time-in-violation.  → {tenant/op_class: in_violation}."""
        now = self.clock() if now is None else now
        out = {}
        with self._lock:
            for (tenant, klass), lane in self._lanes.items():
                target = self.target_ms(klass)
                if target is None:
                    continue
                p99_ms = hist_quantile(
                    self._window_counts(lane, now), 0.99) / 1e3
                violating = lane.count > 0 and p99_ms > target
                if lane.in_violation and lane.last_eval is not None:
                    lane.violation_s += now - lane.last_eval
                lane.in_violation = violating
                lane.last_eval = now
                out[f"{tenant}/{klass}"] = violating
        return out

    # -- reporting ---------------------------------------------------------
    def report(self) -> dict:
        """The full scenario report: per-tenant/per-class quantiles +
        counts, goodput vs offered load, violation time.  JSON-safe —
        this dict rides `mgr_command("slo ingest")` into the
        telemetry spine / exporter."""
        now = self.clock()
        elapsed = (now - self._t0) if self._t0 is not None else 0.0
        denom = max(elapsed, 1e-9)
        tenants: dict[str, dict] = {}
        total_good = total_count = 0
        with self._lock:
            for (tenant, klass), lane in sorted(self._lanes.items()):
                qs = {f"p{q}".replace(".", "") + "_ms":
                      hist_quantile(lane.hist.data[0],
                                    float(f"0.{q}")) / 1e3
                      for q in ("50", "99", "999")}
                total_good += lane.good
                total_count += lane.count
                tenants.setdefault(tenant, {})[klass] = {
                    **qs,
                    "count": lane.count,
                    "ok": lane.ok,
                    "good": lane.good,
                    "throttled": lane.throttled,
                    "errors": lane.errors,
                    "mean_ms": (lane.lat_sum / lane.count * 1e3
                                if lane.count else 0.0),
                    "goodput_ops": lane.good / denom,
                    "slo_ms": self.target_ms(klass),
                    "in_violation": lane.in_violation,
                    "violation_s": lane.violation_s,
                }
            offered = self._offered
        return {
            "elapsed_s": elapsed,
            "offered_ops": offered,
            "offered_rate": (offered / max(self._duration, 1e-9)
                             if self._duration else offered / denom),
            "completed_ops": total_count,
            "goodput_ops": total_good / denom,
            "tenants": tenants,
        }

"""Open-loop load generation — seeded arrival schedules + a worker
pool that never waits for a completion to issue the next op.

Closed-loop drivers (issue → wait → issue) hide queueing collapse:
when the server slows down, a closed loop slows its OFFERED load with
it, so the measured latency stays flat right up to the cliff that
production traffic — which does not politely back off — falls over.
The open-loop generator here issues ops at their scheduled arrival
times regardless of completions (the wrk2/"coordinated omission"
discipline): the arrival schedule is a **pure function of the logged
seed** (replay = identical schedule, the acceptance hook), and the
only honesty metric is *issue-time drift* — how far behind the
schedule the pool fell.

Op classes span the three client surfaces (mixed traffic per ROADMAP
item 2): ``s3_put``/``s3_get`` (RGW), ``rbd_write``/``rbd_read``,
``fs_write``/``fs_read``.  The generator itself is transport-
agnostic — an *executor* callable maps an `OpRecord` onto a real
client call; `workload/scenarios.py` builds those.
"""

from __future__ import annotations

import queue
import random
import threading
import time

# op classes (each maps to one client-surface call in scenarios.py)
S3_PUT = "s3_put"
S3_GET = "s3_get"
RBD_WRITE = "rbd_write"
RBD_READ = "rbd_read"
FS_WRITE = "fs_write"
FS_READ = "fs_read"


class Throttled(Exception):
    """The server shed this op (503 SlowDown).  Counted separately
    from hard errors: shedding under overload is the *correct*
    bounded-admission behavior, not a crash."""


class ArrivalSchedule:
    """Deterministic arrival times on [0, duration): a pure function
    of (kind, rate, duration, seed) so a run replays exactly from its
    logged seed."""

    def __init__(self, times: list[float], *, kind: str, rate: float,
                 duration: float, seed: int):
        self.times = times
        self.kind = kind
        self.rate = float(rate)
        self.duration = float(duration)
        self.seed = int(seed)

    @classmethod
    def fixed(cls, rate: float, duration: float,
              seed: int = 0) -> "ArrivalSchedule":
        """Constant inter-arrival gap 1/rate (deterministic even
        without the seed; it is carried for the replay log)."""
        n = int(rate * duration)
        return cls([i / rate for i in range(n)], kind="fixed",
                   rate=rate, duration=duration, seed=seed)

    @classmethod
    def poisson(cls, rate: float, duration: float,
                seed: int = 0) -> "ArrivalSchedule":
        """Exponential inter-arrivals from a seeded RNG — the
        memoryless arrivals real multi-tenant front doors see."""
        rng = random.Random(seed)
        times, t = [], 0.0
        while True:
            t += rng.expovariate(rate)
            if t >= duration:
                break
            times.append(t)
        return cls(times, kind="poisson", rate=rate,
                   duration=duration, seed=seed)

    @classmethod
    def build(cls, kind: str, rate: float, duration: float,
              seed: int = 0) -> "ArrivalSchedule":
        if kind == "fixed":
            return cls.fixed(rate, duration, seed)
        if kind == "poisson":
            return cls.poisson(rate, duration, seed)
        raise ValueError(f"unknown schedule kind {kind!r}")

    def __len__(self):
        return len(self.times)


class OpMix:
    """Weighted op-class mix; the draw sequence is seeded alongside
    the arrival schedule so replay reproduces not just WHEN ops fire
    but WHAT each one is."""

    def __init__(self, weights: dict[str, float]):
        items = [(k, float(w)) for k, w in weights.items() if w > 0]
        if not items:
            raise ValueError("empty op mix")
        self.classes = [k for k, _ in items]
        self.weights = [w for _, w in items]

    @classmethod
    def s3_default(cls) -> "OpMix":
        return cls({S3_PUT: 3, S3_GET: 7})

    def draw(self, rng: random.Random, n: int) -> list[str]:
        return rng.choices(self.classes, weights=self.weights, k=n)


class OpRecord:
    """One scheduled op: everything the executor needs, plus the
    schedule bookkeeping the drift metric reads."""

    __slots__ = ("tenant", "op_class", "t_sched", "seq", "size")

    def __init__(self, tenant: str, op_class: str, t_sched: float,
                 seq: int, size: int):
        self.tenant = tenant
        self.op_class = op_class
        self.t_sched = t_sched
        self.seq = seq
        self.size = size

    def __repr__(self):
        return (f"OpRecord({self.tenant}:{self.op_class}"
                f"@{self.t_sched:.4f}#{self.seq})")


class TenantProfile:
    """One tenant's traffic: rate, schedule kind, op mix, object
    size.  `ops(duration)` expands it into the deterministic op list
    — same profile + same duration ⇒ byte-identical schedule."""

    def __init__(self, name: str, rate: float, *,
                 kind: str = "poisson", mix: OpMix | None = None,
                 size: int = 4096, seed: int = 0):
        self.name = name
        self.rate = float(rate)
        self.kind = kind
        self.mix = mix or OpMix.s3_default()
        self.size = int(size)
        self.seed = int(seed)

    def schedule(self, duration: float) -> ArrivalSchedule:
        return ArrivalSchedule.build(self.kind, self.rate, duration,
                                     self.seed)

    def ops(self, duration: float) -> list[OpRecord]:
        sched = self.schedule(duration)
        # the mix stream gets its own derived seed: inserting arrivals
        # must not perturb WHICH ops the survivors are
        classes = self.mix.draw(random.Random(self.seed ^ 0x5EED),
                                len(sched))
        return [OpRecord(self.name, k, t, i, self.size)
                for i, (t, k) in enumerate(zip(sched.times, classes))]


def merge_profiles(profiles: list[TenantProfile],
                   duration: float) -> list[OpRecord]:
    """The combined multi-tenant schedule, in arrival order (ties
    break deterministically by tenant name + seq)."""
    ops = [op for p in profiles for op in p.ops(duration)]
    ops.sort(key=lambda o: (o.t_sched, o.tenant, o.seq))
    return ops


class LoadGenerator:
    """Drive a merged multi-tenant schedule open-loop.

    One issuer thread releases each op into the worker queue at its
    scheduled time — it NEVER waits for a completion.  `workers` pool
    threads execute ops via `execute(op)`; if they all lag, the queue
    grows and per-op *issue drift* (worker-pickup time minus
    scheduled time) records exactly how far the system fell behind
    the offered load.  `tracker` (an `slo.SLOTracker`) gets every
    completion."""

    def __init__(self, profiles: list[TenantProfile], execute, *,
                 duration: float, workers: int = 8, tracker=None):
        self.profiles = profiles
        self.execute = execute
        self.duration = float(duration)
        self.workers = max(1, int(workers))
        self.tracker = tracker
        self.ops = merge_profiles(profiles, self.duration)
        self._q: queue.Queue = queue.Queue()
        self._drifts: list[float] = []
        self._lock = threading.Lock()
        self.counts = {"issued": 0, "ok": 0, "throttled": 0,
                       "errors": 0, "abandoned": 0}
        self.error_samples: list[str] = []
        self._stopped = threading.Event()

    def stop(self):
        """Abandon the unexecuted remainder of the schedule: the
        issuer stops releasing, already-queued ops are counted as
        ``abandoned`` instead of executed (in-flight ops finish).
        For flood sources whose backlog nobody needs to drain —
        e.g. a throttled noisy neighbor whose measurement window
        has closed."""
        self._stopped.set()

    def _issuer(self, t0: float):
        for op in self.ops:
            delay = t0 + op.t_sched - time.monotonic()
            if delay > 0 and self._stopped.wait(delay):
                return
            if self._stopped.is_set():
                return
            self._q.put(op)
            with self._lock:
                self.counts["issued"] += 1

    def _worker(self, t0: float):
        while True:
            op = self._q.get()
            if op is None:
                return
            if self._stopped.is_set():
                with self._lock:
                    self.counts["abandoned"] += 1
                continue
            start = time.monotonic()
            drift = start - (t0 + op.t_sched)
            ok, throttled, err = True, False, None
            try:
                self.execute(op)
            except Throttled:
                ok, throttled = False, True
            except Exception as e:      # noqa: BLE001 — the harness
                ok, err = False, str(e)     # must outlive bad ops
            latency = time.monotonic() - start
            with self._lock:
                self._drifts.append(drift)
                if ok:
                    self.counts["ok"] += 1
                elif throttled:
                    self.counts["throttled"] += 1
                else:
                    self.counts["errors"] += 1
                    if len(self.error_samples) < 8:
                        self.error_samples.append(
                            f"{op.op_class}: {err}")
            if self.tracker is not None:
                self.tracker.record(op.tenant, op.op_class, latency,
                                    ok=ok, throttled=throttled)

    def run(self) -> dict:
        """Execute the whole schedule; → the open-loop report."""
        t0 = time.monotonic()
        if self.tracker is not None:
            self.tracker.start(t0=t0, offered=len(self.ops),
                               duration=self.duration)
        threads = [threading.Thread(target=self._worker, args=(t0,),
                                    name=f"wl-worker-{i}",
                                    daemon=True)
                   for i in range(self.workers)]
        for t in threads:
            t.start()
        issuer = threading.Thread(target=self._issuer, args=(t0,),
                                  name="wl-issuer", daemon=True)
        issuer.start()
        issuer.join()
        for _ in threads:
            self._q.put(None)
        for t in threads:
            t.join()
        elapsed = time.monotonic() - t0
        with self._lock:
            drifts = sorted(self._drifts)
            counts = dict(self.counts)
        n = len(drifts)
        mean_drift = (sum(drifts) / n) if n else 0.0
        p99_drift = drifts[min(n - 1, int(0.99 * n))] if n else 0.0
        return {
            "offered_ops": len(self.ops),
            "offered_rate": (len(self.ops) / self.duration
                             if self.duration else 0.0),
            "elapsed_s": elapsed,
            "seeds": {p.name: p.seed for p in self.profiles},
            "mean_drift_s": mean_drift,
            "p99_drift_s": p99_drift,
            "max_drift_s": drifts[-1] if n else 0.0,
            # the honesty metric: mean lateness as a fraction of the
            # schedule's span — <10% means the pool actually kept the
            # offered arrival process
            "drift_pct": (100.0 * mean_drift / self.duration
                          if self.duration else 0.0),
            **counts,
        }

"""Scenario scripts over the open-loop generator + SLO tracker.

Each scenario stands up (or borrows) a MiniCluster, fronts it with
the concurrent RGW gateway, drives a seeded open-loop schedule, and
returns a JSON-safe report — the same dicts `bench.py::_frontdoor_leg`
asserts on and `mgr_command("slo ingest")` publishes to the exporter.

- `steady_state`: one tenant at a fixed offered rate; the baseline.
- `ramp_to_collapse`: geometric rate ramp until the p99 SLO breaks or
  goodput detaches from offered load — the reported ``knee_rate`` is
  the last sustainable step (closed-loop benches can't see this
  knee; an open loop falls off it).
- `noisy_neighbor`: victim + aggressor tenants; the aggressor is
  capped via per-tenant mClock QoS
  (``osd_mclock_scheduler_client_qos``), and the victim's p99 must
  hold near its solo-run p99.
- `game_day_under_load`: the PR 6 stretch site-loss drill with the
  SLO tracker live through blackout → degraded writes → heal.
- `smoke`: the tier-1 fast path (~2 s, 50 ops/s): asserts nothing
  itself, returns drift/error numbers for the test to check.

Every scenario logs its seeds in the report; replaying with the same
seeds reproduces the identical arrival schedule
(`schedule_fingerprint` is the acceptance hook).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time

from .generator import (S3_GET, S3_PUT, RBD_READ, RBD_WRITE, FS_READ,
                        FS_WRITE, LoadGenerator, OpMix, TenantProfile,
                        Throttled, merge_profiles)
from .slo import SLOTracker

DEFAULT_SLO_MS = {S3_PUT: 250.0, S3_GET: 150.0, "*": 300.0}


def schedule_fingerprint(profiles: list[TenantProfile],
                         duration: float) -> str:
    """Digest of the merged arrival schedule — equal seeds/profiles ⇒
    equal fingerprint (the scenario-replay acceptance criterion)."""
    h = hashlib.sha256()
    for op in merge_profiles(profiles, duration):
        h.update(f"{op.tenant}|{op.op_class}|{op.t_sched:.9f}|"
                 f"{op.seq}\n".encode())
    return h.hexdigest()


def _payload(size: int, seq: int) -> bytes:
    """Deterministic, non-constant payload (dedup/compression lanes
    shouldn't collapse every op into one chunk)."""
    stamp = f"{seq:016d}".encode()
    reps = (size + 63) // 64
    return (hashlib.sha256(stamp).digest() * 2 * reps)[:size]


def make_executor(s3=None, *, bucket: str = "wl",
                  rbd_image=None, fs=None, prefill: int = 16,
                  slots: int = 64):
    """Map `OpRecord`s onto real client calls.  `s3` is one S3Client
    or {tenant: S3Client} (per-tenant clients carry the QoS-tag
    header).  RBD/CephFS handles are optional; their ops serialize on
    a small lock (those clients are not thread-safe) — the mixed-op
    point is exercising all three surfaces, not maximizing RBD
    throughput."""
    rbd_lock = threading.Lock()
    fs_lock = threading.Lock()

    def _s3(op):
        return s3[op.tenant] if isinstance(s3, dict) else s3

    def execute(op):
        data = _payload(op.size, op.seq)
        if op.op_class == S3_PUT:
            st, _ = _s3(op).put(bucket,
                                f"{op.tenant}-{op.seq % slots}", data)
            if st == 503:
                raise Throttled()
            if st != 200:
                raise RuntimeError(f"PUT -> {st}")
        elif op.op_class == S3_GET:
            st, _ = _s3(op).get(bucket, f"warm-{op.seq % prefill}")
            if st == 503:
                raise Throttled()
            if st != 200:
                raise RuntimeError(f"GET -> {st}")
        elif op.op_class == RBD_WRITE:
            with rbd_lock:
                rbd_image.write((op.seq % slots) * op.size, data)
        elif op.op_class == RBD_READ:
            with rbd_lock:
                rbd_image.read((op.seq % slots) * op.size, op.size)
        elif op.op_class == FS_WRITE:
            with fs_lock:
                fs.write_file(f"/wl-{op.seq % slots}", data)
        elif op.op_class == FS_READ:
            with fs_lock:
                fs.read_file(f"/wl-{op.seq % prefill}")
        else:
            raise RuntimeError(f"unknown op class {op.op_class}")

    return execute


def _prefill(s3, bucket: str, prefill: int, size: int):
    s3.make_bucket(bucket)
    for i in range(prefill):
        st, _ = s3.put(bucket, f"warm-{i}", _payload(size, i))
        if st != 200:
            raise RuntimeError(f"prefill PUT -> {st}")


def _run_tracked(gen: LoadGenerator, tracker: SLOTracker) -> dict:
    """gen.run() with a live violation-integrator tick alongside."""
    stop = threading.Event()

    def _ticker():
        while not stop.wait(0.25):
            tracker.evaluate()

    t = threading.Thread(target=_ticker, name="slo-eval", daemon=True)
    t.start()
    try:
        open_loop = gen.run()
    finally:
        stop.set()
        t.join(timeout=2.0)
    tracker.evaluate()
    return {"open_loop": open_loop, "slo": tracker.report()}


def publish_slo(rados, report: dict, *, scenario: str = "") -> bool:
    """Push a scenario report into the mgr telemetry spine ("slo
    ingest") for the exporter's ceph_slo_* gauges.  → False when no
    active mgr answered (scenarios run fine without one)."""
    try:
        rc, _outs, _out = rados.mgr_command(
            {"prefix": "slo ingest", "scenario": scenario,
             "report": report}, timeout=5.0)
        return rc == 0
    except Exception:   # noqa: BLE001 — publication is optional
        return False


class _Rig:
    """Cluster + gateway + warmed bucket, shared by the scenarios.
    Owns (and tears down) whatever it created; borrows what the
    caller passed in."""

    def __init__(self, cluster=None, *, n_osds: int = 3,
                 osd_config: dict | None = None, gw_kw: dict
                 | None = None, prefill: int = 16,
                 size: int = 4096, tenants=("tenantA",)):
        from ..vstart import MiniCluster
        from ..rgw import S3Client
        self._own = cluster is None
        if cluster is None:
            cluster = MiniCluster(n_mons=1, n_osds=n_osds,
                                  osd_config=osd_config).start()
        self.cluster = cluster
        self.rados = cluster.rados()
        self.gw = cluster.start_rgw(self.rados, **(gw_kw or {}))
        self.bucket = "wl"
        self.s3 = {t: S3Client("127.0.0.1", self.gw.port, tenant=t)
                   for t in tenants}
        first = next(iter(self.s3.values()))
        _prefill(first, self.bucket, prefill, size)
        self.prefill = prefill

    def executor(self, **kw):
        kw.setdefault("prefill", self.prefill)
        return make_executor(self.s3, bucket=self.bucket, **kw)

    def close(self):
        if self._own:
            self.cluster.stop()


def steady_state(*, rate: float = 100.0, duration: float = 3.0,
                 seed: int = 7, workers: int = 16, size: int = 4096,
                 kind: str = "poisson", mix: OpMix | None = None,
                 slo_ms: dict | None = None, cluster=None,
                 rbd_image=None, fs=None, publish: bool = False,
                 tenant: str = "tenantA") -> dict:
    """One tenant, one sustained offered rate."""
    rig = _Rig(cluster, tenants=(tenant,), size=size)
    try:
        profile = TenantProfile(tenant, rate, kind=kind, mix=mix,
                                size=size, seed=seed)
        tracker = SLOTracker(slo_ms or DEFAULT_SLO_MS)
        gen = LoadGenerator(
            [profile],
            rig.executor(rbd_image=rbd_image, fs=fs),
            duration=duration, workers=workers, tracker=tracker)
        out = _run_tracked(gen, tracker)
        out["fingerprint"] = schedule_fingerprint([profile], duration)
        if publish:
            publish_slo(rig.rados, out["slo"],
                        scenario="steady_state")
        return out
    finally:
        rig.close()


def smoke(*, rate: float = 50.0, duration: float = 2.0,
          seed: int = 5, workers: int = 8, cluster=None) -> dict:
    """The tier-1 fast path: fixed-rate schedule, small objects."""
    return steady_state(rate=rate, duration=duration, seed=seed,
                        workers=workers, size=2048, kind="fixed",
                        cluster=cluster)


def ramp_to_collapse(*, start_rate: float = 40.0,
                     factor: float = 2.0, steps: int = 4,
                     step_duration: float = 2.0,
                     slo_p99_ms: float = 150.0, seed: int = 11,
                     workers: int = 16, size: int = 4096,
                     cluster=None) -> dict:
    """Geometric ramp; → per-step numbers + the knee.

    ``knee_rate``: the highest offered rate whose windowed p99 held
    the SLO *and* whose goodput stayed ≥90% of offered — the number a
    capacity plan can actually use.  ``collapse_rate``: the first
    step past it (None when the ramp never collapsed — raise the
    ceiling or the step count)."""
    rig = _Rig(cluster, tenants=("ramp",), size=size)
    try:
        execute = rig.executor()
        out_steps = []
        knee = collapse = None
        rate = start_rate
        for step in range(steps):
            tracker = SLOTracker({S3_GET: slo_p99_ms,
                                  S3_PUT: slo_p99_ms,
                                  "*": slo_p99_ms})
            profile = TenantProfile("ramp", rate, kind="poisson",
                                    size=size, seed=seed + step)
            gen = LoadGenerator([profile], execute,
                                duration=step_duration,
                                workers=workers, tracker=tracker)
            res = _run_tracked(gen, tracker)
            slo = res["slo"]
            lanes = slo["tenants"].get("ramp", {})
            p99 = max((lane["p99_ms"] for lane in lanes.values()),
                      default=0.0)
            offered = slo["offered_rate"]
            good = slo["goodput_ops"]
            holds = (p99 <= slo_p99_ms
                     and good >= 0.9 * offered
                     and res["open_loop"]["errors"] == 0)
            out_steps.append({
                "rate": rate, "p99_ms": p99,
                "offered_rate": offered, "goodput_ops": good,
                "drift_pct": res["open_loop"]["drift_pct"],
                "throttled": res["open_loop"]["throttled"],
                "holds_slo": holds,
            })
            if holds:
                knee = rate
            elif collapse is None:
                collapse = rate
                break       # past the knee: further steps only melt
            rate *= factor
        return {"steps": out_steps, "knee_rate": knee,
                "collapse_rate": collapse, "slo_p99_ms": slo_p99_ms,
                "seed": seed}
    finally:
        rig.close()


def _topk_top1_client(cluster):
    """Merge the per-OSD heavy-hitter sketches (client dimension) and
    return the cluster-wide #1 key by BYTES, or None when the
    sketches are off or empty (procs-mode handles expose no
    in-process OSD).  Bytes, not ops: the aggressor's execution is
    mClock-capped, so by executed-op count a well-behaved GET tenant
    can legitimately outrank it — the damage it offers the cluster is
    its write volume, which the cap cannot disguise."""
    from ..core import topk as _topk
    dumps = []
    for osd in getattr(cluster, "osds", {}).values():
        tk = getattr(osd, "topk", None)
        if tk is not None and tk.enabled:
            d = tk.dump().get("clients")
            if d and d.get("entries"):
                dumps.append(d)
    if not dumps:
        return None
    rows = _topk.rank(_topk.merge_sketches(dumps), by="bytes", n=1)
    return rows[0]["key"] if rows else None


def noisy_neighbor(*, victim_rate: float = 30.0,
                   aggressor_rate: float = 200.0,
                   duration: float = 3.0, seed: int = 23,
                   workers: int = 16, aggressor_limit: float = 60.0,
                   size: int = 4096, cluster=None) -> dict:
    """Two tenants on one gateway: a well-behaved victim (GETs at a
    modest rate) and an aggressor (PUT flood).  The aggressor's
    tenant tag is capped via per-tenant mClock QoS, so the victim's
    p99 must stay close to its solo-run p99 — the flat-victim-p99
    acceptance check reads ``p99_ratio``.

    Each tenant drives its own generator worker pool (as separate
    client fleets would): the aggressor's in-flight requests are
    bounded by ITS pool, so the shared resource under test is the
    OSD scheduler — where the per-tenant cap lives — not the test
    harness's own thread pool."""
    # both halves of per-tenant QoS: the aggressor gets a LIMIT (hard
    # ops/s ceiling on its private limit stream), the victim gets a
    # RESERVATION (its ops ride the reservation clock ahead of the
    # aggressor's weight-based share) — limit alone still lets the
    # aggressor's allowed rate contend the victim's p99 upward
    qos = {"rgw:aggressor": [0.0, 1.0, float(aggressor_limit)],
           "rgw:victim": [float(victim_rate) * 1.2, 2.0, 0.0]}
    rig = _Rig(cluster,
               osd_config={
                   "osd_op_queue": "mclock",
                   "osd_mclock_scheduler_client_qos":
                       json.dumps(qos)},
               tenants=("victim", "aggressor"), size=size)
    try:
        inner = rig.executor()
        # the tracker's log2 buckets quantize p99 to powers of two —
        # adjacent buckets differ by exactly 2x, so a 1.5x ratio bar
        # on bucket upper-bounds false-fails whenever the true p99
        # sits near an edge.  The ratio therefore comes from EXACT
        # victim latencies sampled here; the histogram numbers stay
        # in the solo/duo sub-reports for the exporter
        samples: dict[str, list[float]] = {"solo": [], "duo": []}
        phase = {"cur": "solo"}

        def execute(op):
            t0 = time.monotonic()
            inner(op)
            if op.tenant == "victim":
                samples[phase["cur"]].append(time.monotonic() - t0)

        def _exact_p99_ms(tag):
            lat = sorted(samples[tag])
            if not lat:
                return 0.0
            return lat[min(len(lat) - 1,
                           int(0.99 * len(lat)))] * 1e3

        vmix = OpMix({S3_GET: 1})
        amix = OpMix({S3_PUT: 1})
        vworkers = max(2, workers // 2)
        aworkers = max(2, workers - vworkers)
        tracker_solo = SLOTracker(DEFAULT_SLO_MS)
        victim = TenantProfile("victim", victim_rate, kind="poisson",
                               mix=vmix, size=size, seed=seed)
        gen = LoadGenerator([victim], execute, duration=duration,
                            workers=vworkers, tracker=tracker_solo)
        solo = _run_tracked(gen, tracker_solo)
        solo_p99 = _exact_p99_ms("solo")

        phase["cur"] = "duo"
        # attribution accuracy rides this drill: clear the per-OSD
        # top-K sketches so the duo window alone decides whether the
        # sketch's #1 client is the injected aggressor tenant
        for osd in getattr(rig.cluster, "osds", {}).values():
            tk = getattr(osd, "topk", None)
            if tk is not None:
                tk.reset()
        tracker_duo = SLOTracker(DEFAULT_SLO_MS)
        aggressor = TenantProfile("aggressor", aggressor_rate,
                                  kind="poisson", mix=amix,
                                  size=size, seed=seed + 1)
        vgen = LoadGenerator([victim], execute, duration=duration,
                             workers=vworkers, tracker=tracker_duo)
        agen = LoadGenerator([aggressor], execute,
                             duration=duration, workers=aworkers,
                             tracker=tracker_duo)
        agg_out: dict = {}

        def _flood():
            agg_out.update(agen.run())

        at = threading.Thread(target=_flood, name="nn-aggressor",
                              daemon=True)
        at.start()
        duo = _run_tracked(vgen, tracker_duo)
        # the victim's measurement window is closed: abandon the
        # aggressor's remaining backlog rather than draining it —
        # each PUT fans into several RADOS ops and the per-tenant
        # limit caps those, so a full drain takes
        # offered * ops_per_put / limit seconds for nothing
        agen.stop()
        at.join(timeout=120.0)
        if at.is_alive():
            raise TimeoutError("aggressor flood never drained")
        duo["open_loop_aggressor"] = agg_out
        duo_p99 = _exact_p99_ms("duo")
        agg = duo["slo"]["tenants"]["aggressor"][S3_PUT]
        top1 = _topk_top1_client(rig.cluster)
        return {
            "solo_p99_ms": solo_p99,
            "duo_p99_ms": duo_p99,
            # floor the denominator: a sub-ms solo p99 would turn
            # scheduling noise into a huge ratio
            "p99_ratio": duo_p99 / max(solo_p99, 1.0),
            "victim_errors": duo["open_loop"]["errors"],
            "aggressor_goodput_ops": agg["goodput_ops"],
            "aggressor_offered": aggressor_rate,
            "aggressor_limit": aggressor_limit,
            # workload attribution: did the space-saving sketch's
            # heaviest client match the tenant we know flooded?
            "top1_client": top1,
            "top1_is_culprit": top1 == "rgw:aggressor",
            "solo": solo, "duo": duo, "seed": seed,
        }
    finally:
        rig.close()


def regime_shift(*, cluster=None, base_rate: float = 60.0,
                 phase_duration: float = 2.0, seed: int = 17,
                 workers: int = 12, size: int = 4096,
                 large_size: int = 65536, recovery: bool = True,
                 slo_ms: dict | None = None, publish: bool = True,
                 scenario: str = "regime_shift") -> dict:
    """The autotuner proving ground: one rig, four load regimes in
    sequence — steady → bursty → large-object → recovery-storm — so a
    config tuned for any single phase is wrong for another.  Each
    phase runs its own seeded open-loop schedule and SLO tracker, and
    publishes its report to the mgr (``slo ingest``) mid-run so a
    live controller sees the pressure *while the next phase runs*.

    Returns per-phase p99/goodput/violation numbers plus
    ``sustained_MBps`` (goodput bytes over total measured time) and
    ``worst_p99_ms`` — the two scalars the bench compares between
    static configs and the controller.  Seeds are per-phase
    (``seed + phase_index``); fingerprints make replays checkable."""
    rig = _Rig(cluster, tenants=("shift",), size=size)
    try:
        slo = dict(slo_ms or DEFAULT_SLO_MS)
        phases = [
            ("steady", base_rate, size, None),
            ("bursty", base_rate * 3.0, size, None),
            ("large_object", max(8.0, base_rate / 4.0), large_size,
             OpMix({S3_PUT: 1})),
            ("recovery_storm", base_rate, size, None),
        ]
        cl = rig.cluster
        can_storm = (recovery and hasattr(cl, "crash_osd")
                     and len(getattr(cl, "osds", {})) >= 3)
        out_phases: dict[str, dict] = {}
        fingerprints: dict[str, str] = {}
        good_bytes = 0.0
        elapsed = 0.0
        worst_p99 = 0.0
        for i, (name, rate, psize, mix) in enumerate(phases):
            reviver = None
            if name == "recovery_storm" and can_storm:
                victim = max(cl.osds)
                cl.crash_osd(victim)
                # revive mid-phase: backfill then storms the cluster
                # while the remaining schedule is still offered
                reviver = threading.Timer(
                    phase_duration / 3.0,
                    lambda: cl.revive_osd(victim, timeout=30.0))
                reviver.daemon = True
                reviver.start()
            profile = TenantProfile("shift", rate, kind="poisson",
                                    mix=mix, size=psize,
                                    seed=seed + i)
            tracker = SLOTracker(slo)
            gen = LoadGenerator([profile], rig.executor(),
                                duration=phase_duration,
                                workers=workers, tracker=tracker)
            res = _run_tracked(gen, tracker)
            if reviver is not None:
                reviver.join(timeout=60.0)
            rep = res["slo"]
            if publish:
                publish_slo(rig.rados, rep, scenario=scenario)
            lanes = rep["tenants"].get("shift", {})
            p99 = max((lane["p99_ms"] for lane in lanes.values()),
                      default=0.0)
            worst_p99 = max(worst_p99, p99)
            good_bytes += (rep["goodput_ops"] * rep["elapsed_s"]
                           * psize)
            elapsed += rep["elapsed_s"]
            out_phases[name] = {
                "rate": rate, "size": psize,
                "p99_ms": p99,
                "goodput_ops": rep["goodput_ops"],
                "goodput_MBps": rep["goodput_ops"] * psize / 1e6,
                "offered_rate": rep["offered_rate"],
                "violation_s": sum(lane["violation_s"]
                                   for lane in lanes.values()),
                "throttled": res["open_loop"]["throttled"],
                "errors": res["open_loop"]["errors"],
            }
            fingerprints[name] = schedule_fingerprint(
                [profile], phase_duration)
        if can_storm:
            cl.wait_for_clean(timeout=60.0)
        return {
            "phases": out_phases,
            "sustained_MBps": (good_bytes / elapsed / 1e6
                               if elapsed else 0.0),
            "worst_p99_ms": worst_p99,
            "recovery_storm": can_storm,
            "seed": seed,
            "fingerprints": fingerprints,
        }
    finally:
        rig.close()


def game_day_under_load(*, rate: float = 30.0,
                        duration: float = 30.0, seed: int = 31,
                        workers: int = 16, size: int = 4096,
                        fault_seed: int = 0x5EED60D) -> dict:
    """The PR 6 stretch site-loss drill with the SLO tracker live:
    blackout the west site mid-schedule, write degraded, heal — the
    tracker's violation clock and the per-phase timings land in one
    report.  PUT-only mix: GETs of warm objects would be served
    through the degraded window for free and mask the stall."""
    from ..vstart import MiniCluster, health_event
    sites = {"east": [0, 1], "west": [2, 3]}
    cluster = MiniCluster(n_mons=5, n_osds=4, stretch_sites=sites,
                          fault_seed=fault_seed).start()
    try:
        r = cluster.rados()
        cluster.enable_stretch_mode(r)
        rig = _Rig(cluster, tenants=("drill",), size=size)
        tracker = SLOTracker(DEFAULT_SLO_MS)
        profile = TenantProfile("drill", rate, kind="poisson",
                                mix=OpMix({S3_PUT: 1}), size=size,
                                seed=seed)
        gen = LoadGenerator([profile], rig.executor(),
                            duration=duration, workers=workers,
                            tracker=tracker)
        result: dict = {}

        def _load():
            result.update(_run_tracked(gen, tracker))

        wl = threading.Thread(target=_load, name="gameday-load",
                              daemon=True)
        wl.start()
        time.sleep(min(2.0, duration / 4))      # steady before chaos
        marks = {}

        def _mark(name):
            def _do(_cl):
                marks[name] = tracker.report()
            return _do

        drill = cluster.game_day([
            {"name": "blackout",
             "action": lambda cl: cl.blackout_site("west"),
             "until": health_event("DEGRADED_STRETCH_MODE", "failed"),
             "timeout": 90.0},
            {"name": "degraded-mark", "action": _mark("degraded")},
            {"name": "heal",
             "action": lambda cl: cl.heal_sites(),
             "until": health_event("DEGRADED_STRETCH_MODE",
                                   "cleared"),
             "timeout": 150.0},
            {"name": "healed-mark", "action": _mark("healed")},
        ])
        wl.join(timeout=duration + 120.0)
        if wl.is_alive():
            raise TimeoutError("load generator never drained")
        cluster.wait_for_clean(timeout=60.0)
        return {**result, "drill": drill, "marks": marks,
                "seed": seed, "fault_seed": fault_seed}
    finally:
        cluster.stop()
